"""Local append-only time-series store for the fleet collector.

Every telemetry surface before this one answered a POINT-IN-TIME
question: a `/metrics` scrape, a heartbeat read, one run JSONL.  Fleet
decisions — "is this replica's p99 burning its SLO", "did that target
stop beating five ticks ago" — need *history*, so the collector lands
every scrape here and the rules engine / dash query the store, never a
live endpoint.

Layout (``<root>/``):

* ``seg-<NNNNNNNN>.jsonl`` — windowed segments, oldest index lowest.
  Line 1 is a header ``{"schema", "segment", "opened_ts"}``; every other
  line is one sample ``{"ts", "name", "labels", "value"}`` or — for
  histogram series — ``{"ts", "name", "labels", "hist": <to_dict>}``
  (the ``obs/hist.py`` snapshot shape, so windows merge with
  ``merge_snapshots`` instead of being resampled).
* the CURRENT segment is rewritten whole via tmp+``os.replace`` on every
  commit — a reader (dash, rules, a human with ``jq``) never sees a torn
  line, the same contract as the heartbeat;
* a segment rolls when it holds ``segment_max_samples`` samples or spans
  ``segment_window_s`` seconds; retention keeps the newest
  ``max_segments`` and unlinks the rest — disk use is bounded by
  construction, not by an operator remembering to prune.

Query API (reader side — works on a store some OTHER process writes):

* :meth:`SeriesStore.range` — raw ``(ts, labels, value)`` samples of one
  metric over a window, labels subset-matched;
* :meth:`SeriesStore.latest` — last sample per distinct label set;
* :meth:`SeriesStore.increase` / :meth:`SeriesStore.rate` — counter
  delta over a window with RESET DETECTION (a restart drops a counter to
  ~0; the increase since the reset still counts, Prometheus-style);
* :meth:`SeriesStore.hist_window` / :meth:`SeriesStore.quantile` —
  histogram-backed quantiles over stored history: snapshots are
  cumulative-since-process-start, so within a window the latest snapshot
  per series rules, and a detected restart (count decreased) folds the
  pre-restart snapshot in via ``merge_snapshots`` — the cross-restart
  composition rule the sidecar already proved, applied to the fleet.

Deliberately stdlib-only and importable WITHOUT the package (the
collector file-loads it beside itself, like the sidecar loads
``recorder.py``) — fleet observability must outlive a wedged jax host.
"""

from __future__ import annotations

import json
import math
import os

if __package__:
    from ..hist import Histogram, merge_snapshots
else:  # file-run: collector.py already file-loaded hist as a sibling
    import importlib.util as _ilu

    _spec = _ilu.spec_from_file_location(
        "_estorch_obs_hist",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     os.pardir, "hist.py"))
    _hist = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_hist)
    Histogram = _hist.Histogram
    merge_snapshots = _hist.merge_snapshots

STORE_SCHEMA = 1
SEGMENT_PREFIX = "seg-"
DEFAULT_MAX_SEGMENTS = 12
DEFAULT_SEGMENT_MAX_SAMPLES = 20000
DEFAULT_SEGMENT_WINDOW_S = 300.0


def _subtract_snapshots(last: dict, anchor: dict | None) -> dict:
    """Bucket-wise ``last - anchor`` for cumulative histogram snapshots
    (the windowed-delta primitive).  No anchor → the whole snapshot.  A
    ladder mismatch or unparseable anchor degrades to the whole snapshot
    (an overcount, never a fabricated distribution); negative deltas
    clamp at 0 (clock skew / torn anchors must not go negative).  The
    raw ``exact`` list never survives subtraction — the delta is
    ladder-only, inside the documented bound."""
    if anchor is None:
        return last
    try:
        h_last = Histogram.from_dict(last)
        h_anchor = Histogram.from_dict(anchor)
        if not h_last._same_ladder(h_anchor):
            return last
    except (ValueError, KeyError, TypeError):
        return last
    h_last._counts = [max(0, a - b) for a, b in
                      zip(h_last._counts, h_anchor._counts)]
    h_last._count = sum(h_last._counts)
    h_last._sum = max(0.0, h_last.sum - h_anchor.sum)
    h_last._exact = None
    # exemplars survive only in buckets the window actually touched — a
    # bucket whose in-window delta is zero must not keep naming a trace
    # id from before the window
    h_last._exemplars = {i: ids for i, ids in h_last._exemplars.items()
                         if h_last._counts[i] > 0}
    return h_last.to_dict()


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def _matches(labels: dict, want: dict | None) -> bool:
    if not want:
        return True
    return all(str(labels.get(k)) == str(v) for k, v in want.items())


class SeriesStore:
    """One store root; writer methods and reader methods are independent
    (a read-only consumer just never calls :meth:`append`)."""

    def __init__(self, root: str, *,
                 max_segments: int = DEFAULT_MAX_SEGMENTS,
                 segment_max_samples: int = DEFAULT_SEGMENT_MAX_SAMPLES,
                 segment_window_s: float = DEFAULT_SEGMENT_WINDOW_S):
        if max_segments < 1 or segment_max_samples < 1:
            raise ValueError("max_segments and segment_max_samples must "
                             "be >= 1")
        self.root = os.path.abspath(root)
        self.max_segments = int(max_segments)
        self.segment_max_samples = int(segment_max_samples)
        self.segment_window_s = float(segment_window_s)
        # writer state: the current segment lives in memory and is
        # committed whole on every append batch (bounded by
        # segment_max_samples, so the rewrite stays cheap)
        self._seg_index: int | None = None
        self._seg_opened_ts: float = 0.0
        self._seg_lines: list[str] = []
        self._seg_samples: int = 0
        # reader cache: path -> (mtime_ns, size, parsed rows).  Rules
        # evaluate R×T queries per tick and the dash ~7 per target per
        # frame; re-JSON-parsing every retained segment for each query
        # would scale the collector's CPU with fleet size squared.  A
        # sealed segment never changes; the current one changes
        # (mtime, size) on every commit and re-parses then.
        self._read_cache: dict[str, tuple[int, int, list[dict]]] = {}

    # ------------------------------------------------------------ paths

    def _seg_path(self, index: int) -> str:
        return os.path.join(self.root, f"{SEGMENT_PREFIX}{index:08d}.jsonl")

    def segments(self) -> list[str]:
        """Retained segment paths, oldest first."""
        try:
            names = sorted(
                n for n in os.listdir(self.root)
                if n.startswith(SEGMENT_PREFIX) and n.endswith(".jsonl"))
        except OSError:
            return []
        return [os.path.join(self.root, n) for n in names]

    # ----------------------------------------------------------- writer

    def _next_index(self) -> int:
        segs = self.segments()
        if not segs:
            return 0
        tail = os.path.basename(segs[-1])[len(SEGMENT_PREFIX):-len(".jsonl")]
        try:
            return int(tail) + 1
        except ValueError:
            return len(segs)

    def _open_segment(self, ts: float) -> None:
        self._seg_index = self._next_index()
        self._seg_opened_ts = float(ts)
        self._seg_lines = [json.dumps({
            "schema": STORE_SCHEMA, "segment": self._seg_index,
            "opened_ts": float(ts)})]
        self._seg_samples = 0

    def _commit(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        path = self._seg_path(self._seg_index)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(self._seg_lines) + "\n")
        os.replace(tmp, path)

    def _enforce_retention(self) -> None:
        segs = self.segments()
        for path in segs[:max(0, len(segs) - self.max_segments)]:
            try:
                os.remove(path)
            except OSError:
                continue  # another pruner won the race: goal state holds

    def append(self, samples: list[dict], ts: float) -> None:
        """Commit one batch of samples stamped ``ts`` (one collection
        tick).  Each sample: ``{"name", "labels", "value"}`` or
        ``{"name", "labels", "hist": <to_dict snapshot>}``."""
        ts = float(ts)
        rolled = False
        if self._seg_index is None:
            self._open_segment(ts)
        elif (self._seg_samples >= self.segment_max_samples
              or ts - self._seg_opened_ts >= self.segment_window_s):
            self._commit()  # seal the finished segment before rolling
            self._open_segment(ts)
            rolled = True
        for s in samples:
            row = {"ts": ts, "name": str(s["name"]),
                   "labels": dict(s.get("labels") or {})}
            if "hist" in s:
                row["hist"] = s["hist"]
            else:
                v = s.get("value")
                if not isinstance(v, (int, float)) or isinstance(v, bool) \
                        or not math.isfinite(v):
                    continue
                row["value"] = float(v)
            self._seg_lines.append(json.dumps(row, default=float))
            self._seg_samples += 1
        self._commit()
        if rolled:
            # prune AFTER the fresh current segment exists on disk, so
            # the retained count never exceeds max_segments even
            # transiently between commits
            self._enforce_retention()

    # ----------------------------------------------------------- reader

    def _segment_rows(self, path: str) -> list[dict]:
        """Parsed sample rows of one segment, memoized on (mtime, size);
        torn/garbage lines are skipped (a reader must never choke on a
        segment some other process is mid-rewrite on)."""
        try:
            st = os.stat(path)
            key = (st.st_mtime_ns, st.st_size)
            cached = self._read_cache.get(path)
            if cached is not None and cached[:2] == key:
                return cached[2]
            with open(path) as f:
                text = f.read()
        except OSError:
            self._read_cache.pop(path, None)
            return []
        rows: list[dict] = []
        for ln in text.splitlines():
            if not ln.strip():
                continue
            try:
                row = json.loads(ln)
            except ValueError:
                continue
            if not isinstance(row, dict) or "name" not in row:
                continue  # header or foreign line
            if isinstance(row.get("ts"), (int, float)):
                rows.append(row)
        self._read_cache[path] = (key[0], key[1], rows)
        return rows

    def _iter_rows(self, since_ts: float):
        """Samples with ts >= since_ts across retained segments, oldest
        first."""
        live = set()
        for path in self.segments():
            live.add(path)
            for row in self._segment_rows(path):
                if row["ts"] >= since_ts:
                    yield row
        for path in list(self._read_cache):
            if path not in live:  # pruned segment: drop its cache too
                del self._read_cache[path]

    def range(self, name: str, labels: dict | None = None,
              window_s: float = 60.0, now: float | None = None
              ) -> list[tuple[float, dict, float]]:
        """``(ts, labels, value)`` scalar samples of ``name`` in the
        window, oldest first; ``labels`` is a subset match."""
        if now is None:
            raise ValueError("range() needs an explicit now= timestamp")
        out = []
        for row in self._iter_rows(now - float(window_s)):
            if row["name"] != name or "value" not in row:
                continue
            if _matches(row.get("labels") or {}, labels):
                out.append((float(row["ts"]), row.get("labels") or {},
                            float(row["value"])))
        out.sort(key=lambda t: t[0])
        return out

    def latest(self, name: str, labels: dict | None = None,
               window_s: float = 60.0, now: float | None = None
               ) -> dict[tuple, tuple[float, dict, float]]:
        """Last sample per distinct full label set in the window."""
        out: dict[tuple, tuple[float, dict, float]] = {}
        for ts, lab, v in self.range(name, labels, window_s, now):
            out[_labels_key(lab)] = (ts, lab, v)
        return out

    def label_values(self, name: str, label: str,
                     window_s: float = 60.0, now: float | None = None
                     ) -> list[str]:
        """Distinct values one label takes on ``name`` samples in the
        window (how the dash discovers targets from the store alone)."""
        vals = set()
        for _ts, lab, _v in self.range(name, None, window_s, now):
            if label in lab:
                vals.add(str(lab[label]))
        return sorted(vals)

    def increase(self, name: str, labels: dict | None = None,
                 window_s: float = 60.0, now: float | None = None
                 ) -> float | None:
        """Counter increase over the window, reset-aware: per series,
        positive deltas accumulate; a drop (process restart zeroed the
        counter) contributes the post-reset value instead of a bogus
        negative.  None when the metric has NO sample in the window —
        "never reported" and "reported, delta 0" are different verdicts
        (the dash renders the former as ``-``)."""
        per_series: dict[tuple, float] = {}
        total = 0.0
        seen = False
        for _ts, lab, v in self.range(name, labels, window_s, now):
            seen = True
            key = _labels_key(lab)
            if key in per_series:
                prev = per_series[key]
                total += (v - prev) if v >= prev else v
            per_series[key] = v
        return total if seen else None

    def rate(self, name: str, labels: dict | None = None,
             window_s: float = 60.0, now: float | None = None) -> float:
        inc = self.increase(name, labels, window_s, now)
        return (inc or 0.0) / float(window_s)

    # ------------------------------------------------------- histograms

    def hist_series(self, name: str, labels: dict | None,
                    window_s: float, now: float | None):
        """``(series key, ts, snapshot)`` triples in ts order for
        histogram samples of ``name`` in the window."""
        if now is None:
            raise ValueError("hist_series() needs an explicit now=")
        for row in self._iter_rows(now - float(window_s)):
            if row["name"] != name or not isinstance(row.get("hist"), dict):
                continue
            if _matches(row.get("labels") or {}, labels):
                yield (_labels_key(row.get("labels") or {}),
                       float(row["ts"]), row["hist"])

    def hist_window(self, name: str, labels: dict | None = None,
                    window_s: float = 60.0, now: float | None = None
                    ) -> Histogram | None:
        """The merged histogram of observations MADE IN the window, or
        None.

        Snapshots are cumulative per source process, so a window's
        distribution is a DELTA: per series and per process incarnation
        (a count drop marks a restart), the last in-window snapshot
        minus the last snapshot from BEFORE the window — without the
        subtraction, a long-dead latency spike would sit in every short
        window forever and a burn-rate alert could never resolve.  A
        restart mid-window folds the buried incarnation's in-window
        delta in via ``merge_snapshots``; a ladder change between
        anchor and snapshot degrades to the whole snapshot (overcount,
        never a fabricated distribution)."""
        if now is None:
            raise ValueError("hist_window() needs an explicit now=")
        start = float(now) - float(window_s)
        # per series: the current incarnation's pre-window anchor +
        # last in-window snapshot, plus finished contributions
        anchor: dict[tuple, dict] = {}
        last_in: dict[tuple, dict] = {}
        prev: dict[tuple, dict] = {}
        contributions: list[dict] = []

        def finalize(key: tuple, buried: bool = False) -> None:
            last = last_in.pop(key, None)
            if last is not None:
                contrib = _subtract_snapshots(last, anchor.get(key))
                if buried and "exemplars" in contrib:
                    # a restart invalidated the source process's trace
                    # rings — its exemplar ids name traces nobody can
                    # assemble anymore, and they must NOT resurrect
                    # into the merged window
                    contrib = {k: v for k, v in contrib.items()
                               if k != "exemplars"}
                contributions.append(contrib)
            anchor.pop(key, None)

        for key, ts, snap in self.hist_series(name, labels,
                                              float(now), now):
            if ts > float(now):
                continue
            p = prev.get(key)
            if p is not None and int(snap.get("count", 0)) < int(
                    p.get("count", 0)):
                finalize(key, buried=True)  # restart: close buried incarnation
            prev[key] = snap
            if ts <= start:
                anchor[key] = snap
            else:
                last_in[key] = snap
        for key in list(last_in):
            finalize(key)
        total: dict | None = None
        for snap in contributions:
            total = merge_snapshots(total, {"_": snap})
        if not total or "_" not in total:
            return None
        try:
            return Histogram.from_dict(total["_"])
        except (ValueError, KeyError, TypeError):
            return None

    def quantile(self, name: str, q: float, labels: dict | None = None,
                 window_s: float = 60.0, now: float | None = None
                 ) -> float | None:
        h = self.hist_window(name, labels, window_s, now)
        if h is None or h.count == 0:
            return None
        return h.quantile(q)
