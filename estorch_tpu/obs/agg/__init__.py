"""estorch_tpu.obs.agg — fleet-scope observability.

Every surface below this package observes ONE process; this package is
the plane that watches N of them at once and remembers what it saw
(docs/observability.md, "Fleet aggregation"):

- **store** — local append-only time-series store: windowed JSONL
  segments (tmp+rename commits, retention by segment count), reset-aware
  counter rates, and histogram-backed quantiles over stored history via
  ``obs/hist.py`` snapshot merges;
- **collector** — the scrape daemon (``python -m estorch_tpu.obs
  collect``): many Prometheus endpoints + heartbeat run-dirs per tick,
  per-target timeouts and consecutive-failure state, everything through
  the one validating parser; exposes its own ``/metrics`` and
  ``/alerts``;
- **rules** — declarative SLO/alert rules (``rules.json``: threshold,
  absence, multi-window burn-rate over histogram-derived p99s) with
  firing/resolved transitions appended to an alerts ledger;
- **dash** — ``obs dash``: the fleet as one terminal table (per-target
  up/down, stored-history latency quantiles, queue depth, recompiles,
  active alerts).

Every module is stdlib-only and file-runnable without the package (the
sidecar's wedged-jax discipline): the fleet plane must keep answering
while the runtime it watches is hung.
"""

from .collector import (Collector, Target, load_targets, scrape_prometheus,
                        scrape_run_dir, validate_targets)
from .dash import fleet_snapshot, render
from .rules import RulesEngine, load_rules, read_ledger, validate_rules
from .store import SeriesStore

__all__ = [
    "Collector",
    "Target",
    "load_targets",
    "validate_targets",
    "scrape_prometheus",
    "scrape_run_dir",
    "SeriesStore",
    "RulesEngine",
    "load_rules",
    "validate_rules",
    "read_ledger",
    "fleet_snapshot",
    "render",
]
