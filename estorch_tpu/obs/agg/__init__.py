"""estorch_tpu.obs.agg — fleet-scope observability.

Every surface below this package observes ONE process; this package is
the plane that watches N of them at once and remembers what it saw
(docs/observability.md, "Fleet aggregation"):

- **store** — local append-only time-series store: windowed JSONL
  segments (tmp+rename commits, retention by segment count), reset-aware
  counter rates, and histogram-backed quantiles over stored history via
  ``obs/hist.py`` snapshot merges;
- **collector** — the scrape daemon (``python -m estorch_tpu.obs
  collect``): many Prometheus endpoints + heartbeat run-dirs per tick,
  per-target timeouts and consecutive-failure state, everything through
  the one validating parser; exposes its own ``/metrics`` and
  ``/alerts``;
- **rules** — declarative SLO/alert rules (``rules.json``: threshold,
  absence, multi-window burn-rate over histogram-derived p99s) with
  firing/resolved transitions appended to an alerts ledger;
- **dash** — ``obs dash``: the fleet as one terminal table (per-target
  up/down, stored-history latency quantiles, queue depth, recompiles,
  desired-vs-actual replica convergence, active alerts);
- **autoscale** — ``obs autoscale``: the closed control loop
  (docs/serving.md, "Autoscaling"): a pure policy step over the
  store's signals + a measured capacity artifact, actuating the
  fleet's ``POST /scale`` and appending every decision to a
  bit-exactly replayable log.

Every module is stdlib-only and file-runnable without the package (the
sidecar's wedged-jax discipline): the fleet plane must keep answering
while the runtime it watches is hung.
"""

from .collector import (Collector, Target, load_targets, scrape_prometheus,
                        scrape_run_dir, validate_targets)
from .autoscale import (Autoscaler, AutoscaleError, decide, load_capacity,
                        read_decisions, replay, validate_capacity)
from .dash import fleet_snapshot, render
from .rules import RulesEngine, load_rules, read_ledger, validate_rules
from .store import SeriesStore

__all__ = [
    "Autoscaler",
    "AutoscaleError",
    "decide",
    "load_capacity",
    "read_decisions",
    "replay",
    "validate_capacity",
    "Collector",
    "Target",
    "load_targets",
    "validate_targets",
    "scrape_prometheus",
    "scrape_run_dir",
    "SeriesStore",
    "RulesEngine",
    "load_rules",
    "validate_rules",
    "read_ledger",
    "fleet_snapshot",
    "render",
]
