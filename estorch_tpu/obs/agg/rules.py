"""Declarative SLO/alert rules over the fleet store.

``rules.json`` is the operator's contract with the fleet: instead of a
human eyeballing N dashboards, the collector evaluates every rule each
collection tick against STORED history and appends firing/resolved
transitions to an alerts ledger — the exact health/capacity/rollback
signal the future fleet router consumes (ROADMAP item 1: canary rollout
"gated on the tail").

Schema (``{"schema": 1, "rules": [...]}``); every rule has a unique
``name`` and a ``kind``:

* ``threshold`` — ``{"metric", "op": ">"|">="|"<"|"<=", "value",
  "for_s": 0, "window_s": 30}``: fires per target when the LATEST stored
  sample of ``metric`` satisfies the predicate continuously for
  ``for_s`` seconds (the classic queue-depth / shed-rate alert);
* ``absence`` — ``{"metric": "estorch_up", "for_s": 0, "window_s": 30}``:
  fires per target when the metric has NO sample in the window **or its
  latest value is 0** — one rule covers both ways a replica dies: the
  endpoint stops answering (no scrape lands) and the endpoint answers
  but reports itself down/stale (``estorch_up 0``, heartbeat-stale);
* ``burn_rate`` — ``{"metric", "quantile": 0.99, "slo_s", "windows":
  [{"window_s": 300, "burn": 1.0}, {"window_s": 30, "burn": 1.0}]}``:
  fires per target when the histogram-derived ``quantile`` over EVERY
  window exceeds ``slo_s × burn`` — the multi-window discipline: the
  long window proves the burn is significant, the short window proves it
  is STILL happening (so a resolved spike stops alerting as soon as the
  short window clears, while a single long window would page for
  minutes after recovery).

Targets are discovered from the store itself (the ``target`` label the
collector stamps on every sample), so a rule written once covers every
replica that ever reports — including ones added after the rules file
was authored.

State machine per (rule, target): ok → pending (condition true, clock
running) → firing (held ``for_s``) → ok again, with ``firing`` /
``resolved`` transitions appended to the ledger (JSONL, atomic append)
and exposed on the collector's ``/alerts``.  Transition messages NAME
the target and the metric/endpoint — an alert an operator must decode
is an alert that gets ignored.

Stdlib-only, file-loadable (the collector/dash wedge contract).
"""

from __future__ import annotations

import json
import os

RULES_SCHEMA = 1
LEDGER_FILENAME = "alerts.jsonl"
# the ledger compacts to this many most-recent transitions on append —
# every reader (seed_from_ledger, /alerts, the dash) uses tail<=500, and
# an unbounded ledger under a flapping rule would grow forever while
# each atomic append re-copies the whole file (O(n^2) cumulative)
LEDGER_MAX_TRANSITIONS = 2000

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


def validate_rules(obj) -> list[str]:
    """Structural problems of a parsed rules file ([] when clean)."""
    problems: list[str] = []
    if not isinstance(obj, dict) or obj.get("schema") != RULES_SCHEMA:
        return [f"rules file must be an object with schema={RULES_SCHEMA}"]
    rules = obj.get("rules")
    if not isinstance(rules, list):
        return ["rules must be a list"]
    seen: set[str] = set()
    for i, r in enumerate(rules):
        where = f"rules[{i}]"
        if not isinstance(r, dict):
            problems.append(f"{where}: not an object")
            continue
        name = r.get("name")
        if not name or not isinstance(name, str):
            problems.append(f"{where}: missing name")
        elif name in seen:
            problems.append(f"{where}: duplicate name {name!r}")
        else:
            seen.add(name)
        kind = r.get("kind")
        if kind not in ("threshold", "absence", "burn_rate"):
            problems.append(f"{where}: unknown kind {kind!r}")
            continue
        if not isinstance(r.get("metric"), str):
            problems.append(f"{where}: missing metric")
        if kind == "threshold":
            if r.get("op") not in _OPS:
                problems.append(f"{where}: op must be one of "
                                f"{sorted(_OPS)}")
            if not isinstance(r.get("value"), (int, float)):
                problems.append(f"{where}: missing numeric value")
        if kind == "burn_rate":
            if not isinstance(r.get("slo_s"), (int, float)) \
                    or r.get("slo_s", 0) <= 0:
                problems.append(f"{where}: slo_s must be > 0")
            q = r.get("quantile", 0.99)
            if not isinstance(q, (int, float)) or not 0.5 <= q < 1.0:
                problems.append(f"{where}: quantile must be in [0.5, 1)")
            wins = r.get("windows")
            if not isinstance(wins, list) or not wins or not all(
                    isinstance(w, dict)
                    and isinstance(w.get("window_s"), (int, float))
                    and w.get("window_s", 0) > 0 for w in wins):
                problems.append(f"{where}: windows must be a non-empty "
                                "list of {window_s[, burn]} objects")
    return problems


def load_rules(path: str) -> "RulesEngine":
    """Parse + validate a rules file; ValueError carries every problem
    on one line (a collector refusing to start must say exactly why)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"{path}: unreadable rules file: {e}") from e
    problems = validate_rules(obj)
    if problems:
        raise ValueError(f"{path}: " + "; ".join(problems))
    return RulesEngine(obj["rules"])


class RulesEngine:
    """Evaluate rules against a store each tick; track alert states."""

    def __init__(self, rules: list[dict], *, ledger_path: str | None = None):
        self.rules = list(rules)
        self.ledger_path = ledger_path
        # (rule name, target) -> {"state", "since_ts", "detail"}
        self._states: dict[tuple[str, str], dict] = {}
        if ledger_path:
            self.seed_from_ledger()

    def seed_from_ledger(self, tail: int = LEDGER_MAX_TRANSITIONS) -> None:
        """Adopt still-firing alerts from the ledger as this engine's
        starting state.  Without this, a collector restart forgets a
        fired alert: if the condition cleared while the collector was
        down, no ``resolved`` is ever appended and the dash (which
        reconstructs active alerts from the ledger) shows a phantom
        firing forever; if it still holds, a duplicate ``firing`` is
        re-announced.  Seeded state makes the next evaluate() emit
        exactly the missing transition."""
        if not self.ledger_path:
            return
        known = {r["name"] for r in self.rules if isinstance(r, dict)}
        for t in read_ledger(self.ledger_path, tail=tail):
            rule, target = str(t.get("rule")), str(t.get("target"))
            key = (rule, target)
            if t.get("event") == "firing" and rule in known:
                self._states[key] = {
                    "state": "firing",
                    "since_ts": float(t.get("ts", 0.0)),
                    "detail": str(t.get("detail", "")),
                }
            elif t.get("event") == "resolved":
                self._states.pop(key, None)

    # -------------------------------------------------------- predicates

    def _condition(self, rule: dict, store, target: str, now: float
                   ) -> tuple[bool, str]:
        """(condition holds, human detail naming target + metric)."""
        metric = rule["metric"]
        labels = {"target": target}
        kind = rule["kind"]
        window_s = float(rule.get("window_s", 30.0))
        if kind == "threshold":
            latest = store.latest(metric, labels, window_s, now)
            if not latest:
                return False, f"no {metric} sample for {target!r}"
            _ts, _lab, v = max(latest.values(), key=lambda t: t[0])
            op, bound = rule["op"], float(rule["value"])
            return (_OPS[op](v, bound),
                    f"{metric}={v:g} {op} {bound:g} on target {target!r}")
        if kind == "absence":
            latest = store.latest(metric, labels, window_s, now)
            if not latest:
                return True, (f"{metric} absent for {window_s:g}s on "
                              f"target {target!r}")
            _ts, _lab, v = max(latest.values(), key=lambda t: t[0])
            return (v == 0.0,
                    f"{metric}={v:g} on target {target!r}")
        # burn_rate: every window's quantile must exceed slo*burn
        q = float(rule.get("quantile", 0.99))
        slo = float(rule["slo_s"])
        worst = None
        for w in rule["windows"]:
            win = float(w["window_s"])
            burn = float(w.get("burn", 1.0))
            got = store.quantile(metric, q, labels, win, now)
            if got is None or got <= slo * burn:
                return False, (f"p{q * 100:g} of {metric} within SLO "
                               f"{slo:g}s on target {target!r}")
            worst = max(worst or 0.0, got)
        return True, (f"p{q * 100:g} of {metric} = {worst:.6g}s breaches "
                      f"SLO {slo:g}s on target {target!r} across all "
                      f"{len(rule['windows'])} windows")

    # -------------------------------------------------------- evaluation

    def evaluate(self, store, targets: list[str], now: float) -> list[dict]:
        """One tick: run every rule against every target; returns the
        transitions (also appended to the ledger when one is configured)."""
        transitions: list[dict] = []
        for rule in self.rules:
            for_s = float(rule.get("for_s", 0.0))
            for target in targets:
                key = (rule["name"], target)
                st = self._states.get(key) or {"state": "ok",
                                               "since_ts": now}
                holds, detail = self._condition(rule, store, target, now)
                state = st["state"]
                if holds:
                    if state == "ok":
                        st = {"state": "pending", "since_ts": now,
                              "detail": detail}
                    if st["state"] == "pending" \
                            and now - st["since_ts"] >= for_s:
                        st = {"state": "firing", "since_ts": now,
                              "detail": detail}
                        transitions.append({
                            "ts": now, "event": "firing",
                            "rule": rule["name"], "kind": rule["kind"],
                            "target": target, "detail": detail,
                        })
                    elif st["state"] == "firing":
                        st["detail"] = detail  # keep the latest reading
                else:
                    if state == "firing":
                        transitions.append({
                            "ts": now, "event": "resolved",
                            "rule": rule["name"], "kind": rule["kind"],
                            "target": target, "detail": detail,
                        })
                    st = {"state": "ok", "since_ts": now}
                self._states[key] = st
        # a target removed from the configuration can never re-evaluate:
        # close its firing alerts instead of haunting /alerts and the
        # dash forever (and being re-adopted by every restart's seed)
        live = set(targets)
        for (rule_name, target), st in list(self._states.items()):
            if target in live:
                continue
            if st["state"] == "firing":
                transitions.append({
                    "ts": now, "event": "resolved", "rule": rule_name,
                    "kind": "removed", "target": target,
                    "detail": f"target {target!r} removed from the "
                              "collector's configuration",
                })
            del self._states[(rule_name, target)]
        if transitions and self.ledger_path:
            append_ledger(self.ledger_path, transitions)
        return transitions

    def active(self) -> list[dict]:
        """Currently-firing alerts, stable order."""
        out = []
        for (rule, target), st in sorted(self._states.items()):
            if st["state"] == "firing":
                out.append({"rule": rule, "target": target,
                            "since_ts": st["since_ts"],
                            "detail": st.get("detail", "")})
        return out


# ---------------------------------------------------------------- ledger

def append_ledger(path: str, transitions: list[dict],
                  max_transitions: int = LEDGER_MAX_TRANSITIONS) -> None:
    """Atomic append (copy + extend + rename, the FlightRecorder dump
    contract): a crash mid-append leaves the previous complete ledger or
    the new complete one, never a torn line for ``/alerts`` or the dash
    to choke on.  Compacts to the newest ``max_transitions`` lines so a
    flapping rule on a long-running collector cannot grow the ledger
    (and the cost of each atomic rewrite) without bound."""
    prev_lines: list[str] = []
    if os.path.exists(path):
        with open(path) as old:
            prev = old.read()
        if prev and not prev.endswith("\n"):
            cut = prev.rfind("\n")
            prev = prev[:cut + 1] if cut >= 0 else ""
        prev_lines = prev.splitlines()
    lines = prev_lines + [json.dumps(t, default=float)
                          for t in transitions]
    lines = lines[-int(max_transitions):]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n" if lines else "")
    os.replace(tmp, path)


def read_ledger(path: str, tail: int = 100) -> list[dict]:
    """Last ``tail`` ledger transitions (torn/garbage lines skipped)."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    out: list[dict] = []
    for ln in lines[-int(tail):]:
        try:
            row = json.loads(ln)
        except ValueError:
            continue
        if isinstance(row, dict):
            out.append(row)
    return out
