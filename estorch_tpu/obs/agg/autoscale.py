"""Autoscaler: the serving fleet closes its own control loop.

``python -m estorch_tpu.obs autoscale --store DIR --fleet-admin
host:port --capacity capacity.json`` watches the collector store ALONE
— no live scrapes, no jax — and drives the fleet's ``POST /scale``
admin surface (serve/fleet.py) so capacity follows offered load without
an operator in the loop (ROADMAP item 1).

Signals (all read from the store for one router target):

* offered load — ``rate()`` of ``estorch_router_requests_total``;
* actual replicas — ``estorch_router_replica_up`` gauges;
* queue pressure — ``estorch_router_replica_queue_depth`` gauges;
* tail vs SLO — histogram-derived p99 of ``estorch_router_route_s``;
* burn-rate alert state — replayed from the collector's
  ``alerts.jsonl`` ledger (rules.py), filtered to the configured
  ``burn_rules``.

Policy (docs/serving.md "Autoscaling"):

* ``target = clamp(ceil(offered_rps × headroom / max_rps_at_slo),
  min_replicas, max_replicas)`` — ``max_rps_at_slo`` comes from the
  persisted capacity model (``loadgen --capacity-sweep --out``), whose
  bundle sha / platform MUST match the fleet's (the autoscaler refuses
  a mismatched model, naming both sides);
* scale-UP to ``target`` when demand says so, rate-limited by
  ``up_cooldown_s``; a firing burn-rate alert BYPASSES the cooldown
  when demand agrees, and steps up one replica per cooldown window even
  when demand math is satisfied (an SLO burning at "enough" capacity
  means the model is optimistic right now);
* scale-DOWN only after a SUSTAINED low-watermark window: utilization
  (``offered / (max_rps × current)``) must sit <= ``low_watermark``
  continuously for ``low_hold_s``, then one replica per
  ``down_cooldown_s`` — the per-direction cooldowns + the dead band
  between ``low_watermark`` and ``1/headroom`` are the hysteresis that
  keeps alert flapping from thrashing the fleet.

Every decision is one structured event on an APPEND-ONLY decision log
(``<store>/autoscale_decisions.jsonl``): the full inputs snapshot, the
policy, the controller state before/after, the verdict, and the
actuation result.  ``--replay LOG`` re-derives every verdict from the
recorded inputs bit-exactly (the house determinism contract applied to
control): :func:`decide` is a pure function of (inputs, policy, state).

Stdlib-only, jax-free, file-runnable (``python
estorch_tpu/obs/agg/autoscale.py --selfcheck``) — the sidecar
discipline: the loop that adds capacity when the fleet drowns must not
depend on the runtime that is drowning.
"""

from __future__ import annotations

import argparse
import http.client
import json
import math
import os
import sys
import threading
import time

if __package__:
    from .rules import LEDGER_FILENAME, read_ledger
    from .store import SeriesStore
else:  # file-run (wedged-jax host): load siblings without package init
    import importlib.util

    def _load(name: str, *rel: str):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            *rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _rules = _load("_estorch_obs_agg_rules", "rules.py")
    _store = _load("_estorch_obs_agg_store", "store.py")
    LEDGER_FILENAME = _rules.LEDGER_FILENAME
    read_ledger = _rules.read_ledger
    SeriesStore = _store.SeriesStore

AUTOSCALE_SCHEMA = 1
# must match serve/loadgen.py CAPACITY_SCHEMA (the artifact contract;
# this module must stay importable without the serve tree)
CAPACITY_SCHEMA = 1
DECISIONS_FILENAME = "autoscale_decisions.jsonl"

# documented policy knobs; a fleet.json autoscale block or CLI flags
# override individual keys
POLICY_DEFAULTS = {
    "headroom": 1.3,          # spare capacity multiplier on demand
    "min_replicas": 1,
    "max_replicas": 8,
    "window_s": 20.0,         # signal window for rate/p99 reads
    "slo_ms": None,           # None: the capacity artifact's slo_ms
    "up_cooldown_s": 10.0,    # min seconds between scale-ups
    "down_cooldown_s": 60.0,  # min seconds between scale-downs
    "low_watermark": 0.6,     # utilization below this arms scale-down
    "low_hold_s": 30.0,       # sustained low window before stepping
    "burn_rules": [],         # alert rule names meaning "step up now"
    "max_rps_at_slo": None,   # injected from the capacity artifact
}

FRESH_STATE = {"desired": None, "last_up_ts": None,
               "last_down_ts": None, "low_since": None}


class AutoscaleError(RuntimeError):
    """Bad capacity model / store / configuration — refuse loudly."""


# ------------------------------------------------------------- capacity

def validate_capacity(obj) -> list[str]:
    """Structural problems of a parsed capacity artifact ([] if clean)."""
    if not isinstance(obj, dict) or obj.get("schema") != CAPACITY_SCHEMA:
        return [f"capacity artifact must be an object with "
                f"schema={CAPACITY_SCHEMA}"]
    problems = []
    if obj.get("kind") != "capacity":
        problems.append("kind: must be 'capacity'")
    rps = obj.get("max_rps_at_slo")
    if rps is None:
        problems.append("max_rps_at_slo: null (the sweep saturated at "
                        "every rung — no usable capacity model)")
    elif not isinstance(rps, (int, float)) or isinstance(rps, bool) \
            or rps <= 0:
        problems.append("max_rps_at_slo: must be a number > 0")
    slo = obj.get("slo_ms")
    if not isinstance(slo, (int, float)) or isinstance(slo, bool) \
            or slo <= 0:
        problems.append("slo_ms: must be a number > 0")
    if not isinstance(obj.get("rungs"), list) or not obj.get("rungs"):
        problems.append("rungs: must be a non-empty list")
    return problems


def load_capacity(path: str) -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise AutoscaleError(
            f"{path}: unreadable capacity artifact: {e}") from e
    problems = validate_capacity(obj)
    if problems:
        raise AutoscaleError(f"{path}: " + "; ".join(problems))
    return obj


def capacity_mismatch(capacity: dict, fleet: dict) -> str | None:
    """Why this capacity model must NOT drive that fleet (None = ok).
    Compares bundle sha and platform when BOTH sides carry them —
    naming both sides, so the refusal is actionable."""
    cap_sha, fleet_sha = capacity.get("bundle_sha"), fleet.get("bundle_sha")
    if cap_sha and fleet_sha and cap_sha != fleet_sha:
        return (f"capacity model measured bundle sha {cap_sha[:12]}… but "
                f"the fleet serves bundle sha {fleet_sha[:12]}… "
                f"({fleet.get('bundle')}) — re-run loadgen "
                f"--capacity-sweep --out against the fleet's bundle")
    cap_plat, fleet_plat = capacity.get("platform"), fleet.get("platform")
    if cap_plat and fleet_plat and cap_plat != fleet_plat:
        return (f"capacity model measured on platform {cap_plat!r} but "
                f"the fleet runs on {fleet_plat!r} — per-replica "
                f"max-RPS does not transfer across platforms")
    return None


# ---------------------------------------------------------- decision log

def append_decision(path: str, event: dict) -> None:
    """Append-only: one JSON line per decision.  A torn tail line (the
    process died mid-write) is skipped by every reader."""
    with open(path, "a") as f:
        f.write(json.dumps(event) + "\n")
        f.flush()


def read_decisions(path: str, tail: int | None = None) -> list[dict]:
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    if tail is not None:
        lines = lines[-int(tail):]
    out = []
    for ln in lines:
        try:
            row = json.loads(ln)
        except ValueError:
            continue
        if isinstance(row, dict) and row.get("event") == "decision":
            out.append(row)
    return out


def _norm(obj) -> str:
    return json.dumps(obj, sort_keys=True)


def replay(path: str) -> dict:
    """Re-derive every logged verdict from its recorded inputs snapshot
    and compare bit-exactly (canonical-JSON equality) — the determinism
    contract: :func:`decide` is pure, so the log IS the controller.
    Also checks the state CHAIN: each decision's ``state_before`` must
    equal the previous ``state_after`` (restart adoption preserves it)."""
    decisions = read_decisions(path)
    mismatches: list[dict] = []
    prev_after: dict | None = None
    for i, ev in enumerate(decisions):
        verdict2, after2 = decide(ev["inputs"], ev["policy"],
                                  ev["state_before"])
        if _norm(verdict2) != _norm(ev.get("verdict")):
            mismatches.append({"index": i, "kind": "verdict",
                               "logged": ev.get("verdict"),
                               "derived": verdict2})
        if _norm(after2) != _norm(ev.get("state_after")):
            mismatches.append({"index": i, "kind": "state_after",
                               "logged": ev.get("state_after"),
                               "derived": after2})
        if (prev_after is not None
                and _norm(ev.get("state_before")) != _norm(prev_after)):
            mismatches.append({"index": i, "kind": "state_chain",
                               "expected": prev_after,
                               "logged": ev.get("state_before")})
        prev_after = ev.get("state_after")
    return {"ok": not mismatches, "decisions": len(decisions),
            "mismatches": mismatches}


# -------------------------------------------------------------- inputs

def _active_alerts(ledger_path: str) -> list[dict]:
    """Replay firing/resolved transitions into the active set (same
    reconstruction the dash uses)."""
    active: dict[tuple, dict] = {}
    for row in read_ledger(ledger_path, tail=2000):
        key = (row.get("rule"), row.get("target"))
        if row.get("event") == "firing":
            active[key] = row
        elif row.get("event") == "resolved":
            active.pop(key, None)
    return list(active.values())


def read_inputs(store, target: str, *, policy: dict, now: float,
                ledger_path: str | None = None) -> dict:
    """One point-in-time snapshot of every policy input, from the store
    alone.  This dict is recorded verbatim in the decision event —
    replay re-derives the verdict from IT, never from the store."""
    window = float(policy["window_s"])
    labels = {"target": target}
    inc = store.increase("estorch_router_requests_total", labels,
                         window, now)
    offered = None if inc is None else inc / window
    ups = store.latest("estorch_router_replica_up", labels, window, now)
    actual = sum(1 for _ts, _lab, v in ups.values() if v == 1.0)
    queues = store.latest("estorch_router_replica_queue_depth", labels,
                          window, now)
    queue_depth = (sum(v for _ts, _lab, v in queues.values())
                   if queues else None)
    p99_s = store.quantile("estorch_router_route_s", 0.99, labels,
                           window, now)
    desired_gauge = store.latest("estorch_router_desired_replicas",
                                 labels, window, now)
    reported_desired = None
    for _ts, _lab, v in desired_gauge.values():
        reported_desired = int(v)
    alerts = (_active_alerts(ledger_path)
              if ledger_path else [])
    alerts = [a for a in alerts if a.get("target") == target]
    burn_rules = set(policy.get("burn_rules") or [])
    return {
        "ts": now,
        "target": target,
        "window_s": window,
        "offered_rps": offered,
        "p99_ms": None if p99_s is None else p99_s * 1e3,
        "queue_depth": queue_depth,
        "actual_replicas": actual,
        "replicas_known": len(ups),
        "reported_desired": reported_desired,
        "alerts_active": sorted(a.get("rule") or "" for a in alerts),
        "burn_firing": sorted((a.get("rule") or "") for a in alerts
                              if (a.get("rule") or "") in burn_rules),
    }


# -------------------------------------------------------------- policy

def decide(inputs: dict, policy: dict, state: dict
           ) -> tuple[dict, dict]:
    """PURE policy step: ``(inputs, policy, state) -> (verdict,
    state_after)``.  No clocks, no I/O, no randomness — the decision
    log replays bit-exactly because nothing here can diverge from the
    recorded snapshot."""
    now = float(inputs["ts"])
    lo = int(policy["min_replicas"])
    hi = int(policy["max_replicas"])
    cap = policy.get("max_rps_at_slo")
    cur = state.get("desired")
    if cur is None:
        cur = inputs.get("actual_replicas") or lo
    cur = max(1, int(cur))
    offered = inputs.get("offered_rps")
    if offered is None or not cap:
        target, util = cur, None  # no signal / no model: hold
    else:
        target = math.ceil(float(offered) * float(policy["headroom"])
                           / float(cap))
        util = float(offered) / (float(cap) * cur)
    target = min(max(target, lo), hi)
    burn = list(inputs.get("burn_firing") or [])
    up_ok = (state.get("last_up_ts") is None
             or now - state["last_up_ts"] >= float(policy["up_cooldown_s"]))
    down_ok = (state.get("last_down_ts") is None
               or now - state["last_down_ts"]
               >= float(policy["down_cooldown_s"]))
    low = (target < cur and util is not None
           and util <= float(policy["low_watermark"]))

    action, desired, reason = "hold", cur, "steady"
    new_state = dict(state)
    new_state["low_since"] = state.get("low_since") if low else None
    if target > cur:
        if burn or up_ok:
            action, desired = "up", target
            reason = ("demand+burn:" + ",".join(burn)) if burn \
                else "demand"
        else:
            reason = "up_cooldown"
    elif burn:
        # demand math satisfied but the SLO is burning: the capacity
        # model is optimistic right now — step one, per cooldown window
        if cur >= hi:
            reason = "burn_at_max"
        elif up_ok:
            action, desired = "up", cur + 1
            reason = "burn:" + ",".join(burn)
        else:
            reason = "burn_cooldown"
    elif low:
        if new_state["low_since"] is None:
            new_state["low_since"] = now
            reason = "low_watermark_arming"
        elif now - new_state["low_since"] < float(policy["low_hold_s"]):
            reason = "low_watermark_holding"
        elif not down_ok:
            reason = "down_cooldown"
        else:
            # one replica per window: a gentle descent re-proves the
            # low watermark at each step instead of free-falling
            action, desired = "down", max(cur - 1, target, lo)
            reason = "low_watermark"
    if action == "up":
        new_state["last_up_ts"] = now
        new_state["low_since"] = None
    elif action == "down":
        new_state["last_down_ts"] = now
        new_state["low_since"] = now  # re-arm: next step holds again
    new_state["desired"] = desired
    verdict = {"action": action, "desired": desired, "current": cur,
               "target": target, "utilization": util, "reason": reason,
               "burn": burn}
    return verdict, new_state


# ------------------------------------------------------------ autoscaler

class Autoscaler:
    """The control loop: read inputs → decide → actuate → log.

    Actuation is either HTTP (``fleet_admin`` host:port — ``POST
    /scale`` on the fleet's router) or a direct callable (``actuate(n,
    reason)`` — the ``--autoscale`` mode embedded in the fleet
    supervisor).  ``fleet_identity`` (or the fleet's ``GET /scale``
    status when actuating over HTTP) is checked against the capacity
    model BEFORE the first actuation — a mismatched model is refused,
    naming both sides."""

    def __init__(self, store_root: str, *, capacity,
                 fleet_admin: str | None = None, actuate=None,
                 fleet_identity: dict | None = None,
                 target: str | None = None, policy: dict | None = None,
                 interval_s: float = 2.0, log_path: str | None = None,
                 dry_run: bool = False):
        self.store_root = os.path.abspath(store_root)
        self.store = SeriesStore(self.store_root)
        self.capacity = (capacity if isinstance(capacity, dict)
                         else load_capacity(str(capacity)))
        problems = validate_capacity(self.capacity)
        if problems:
            raise AutoscaleError("capacity artifact: "
                                 + "; ".join(problems))
        self.policy = {**POLICY_DEFAULTS, **(policy or {})}
        self.policy["max_rps_at_slo"] = self.capacity["max_rps_at_slo"]
        if self.policy.get("slo_ms") is None:
            self.policy["slo_ms"] = self.capacity.get("slo_ms")
        self.fleet_admin = fleet_admin
        self.actuate_fn = actuate
        self.dry_run = bool(dry_run)
        self.target = target
        self.interval_s = float(interval_s)
        self.log_path = log_path or os.path.join(self.store_root,
                                                 DECISIONS_FILENAME)
        self.ledger_path = os.path.join(self.store_root, LEDGER_FILENAME)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks_done = 0
        # first contact over HTTP only makes sense when there is an
        # HTTP admin; callable actuators vouch via fleet_identity
        self._checked_fleet = fleet_admin is None
        if fleet_identity is not None:
            self._refuse_on_mismatch(fleet_identity)
            self._checked_fleet = True
        # restart adoption: the last logged decision's state_after IS
        # the controller state (keeps the replayed chain unbroken and
        # the cooldowns honest across a daemon restart)
        tail = read_decisions(self.log_path, tail=1)
        self.state = (dict(tail[-1]["state_after"]) if tail
                      else dict(FRESH_STATE))

    # ----------------------------------------------------------- fleet

    def _refuse_on_mismatch(self, fleet_identity: dict) -> None:
        why = capacity_mismatch(self.capacity, fleet_identity or {})
        if why:
            append_decision(self.log_path, {
                "schema": AUTOSCALE_SCHEMA, "ts": time.time(),
                "event": "refused", "reason": why})
            raise AutoscaleError(why)

    def _fleet_request(self, method: str, path: str,
                       payload: dict | None = None) -> dict:
        host, _, port = str(self.fleet_admin).partition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10.0)
        try:
            body = (json.dumps(payload).encode()
                    if payload is not None else None)
            conn.request(method, path, body,
                         {"Content-Type": "application/json"}
                         if body else {})
            resp = conn.getresponse()
            data = resp.read()
            try:
                out = json.loads(data.decode() or "{}")
            except ValueError:
                out = {"raw": data[:200].decode(errors="replace")}
            out["_status"] = resp.status
            return out
        finally:
            conn.close()

    def ensure_fleet(self) -> None:
        """First-contact gate (HTTP actuation): fetch the fleet's scale
        status and refuse a mismatched capacity model."""
        if self._checked_fleet:
            return
        try:
            status = self._fleet_request("GET", "/scale")
        except (OSError, http.client.HTTPException) as e:
            raise AutoscaleError(
                f"fleet admin {self.fleet_admin} unreachable: "
                f"{type(e).__name__}: {e}") from e
        self._refuse_on_mismatch(status)
        self._checked_fleet = True

    def _discover_target(self, now: float) -> str | None:
        """The router target this store is watching (unambiguous or
        bust — scaling the wrong fleet is worse than not scaling)."""
        names = self.store.label_values("estorch_router_requests_total",
                                        "target",
                                        float(self.policy["window_s"]),
                                        now)
        if not names:
            return None
        if len(names) > 1 and self.target is None:
            raise AutoscaleError(
                f"multiple router targets in the store ({sorted(names)}) "
                f"— pass --target")
        return sorted(names)[0]

    # ------------------------------------------------------------ loop

    def _actuate(self, desired: int, reason: str) -> dict:
        if self.dry_run:
            return {"attempted": False, "dry_run": True}
        if self.actuate_fn is not None:
            try:
                res = self.actuate_fn(desired, reason)
            except Exception as e:  # noqa: BLE001 — an actuation bug
                # must land in the log, never kill the control loop
                return {"attempted": True, "ok": False,
                        "error": repr(e)[:300]}
            ok = bool(res.get("ok")) if isinstance(res, dict) else True
            return {"attempted": True, "ok": ok, "result": res}
        try:
            res = self._fleet_request("POST", "/scale",
                                      {"replicas": int(desired),
                                       "reason": reason})
        except (OSError, http.client.HTTPException) as e:
            return {"attempted": True, "ok": False,
                    "error": f"{type(e).__name__}: {e}"}
        return {"attempted": True, "ok": res.get("_status") == 200
                and bool(res.get("ok")), "result": res}

    def tick(self, now: float | None = None) -> dict | None:
        """One control cycle; returns the logged decision event (None
        when no router target reports yet)."""
        now = time.time() if now is None else float(now)
        self.ensure_fleet()
        target = self.target or self._discover_target(now)
        if target is None:
            return None
        inputs = read_inputs(self.store, target, policy=self.policy,
                             now=now, ledger_path=self.ledger_path)
        state_before = dict(self.state)
        verdict, state_after = decide(inputs, self.policy, state_before)
        actuation = {"attempted": False}
        if verdict["action"] in ("up", "down"):
            actuation = self._actuate(verdict["desired"],
                                      verdict["reason"])
        event = {
            "schema": AUTOSCALE_SCHEMA,
            "ts": now,
            "event": "decision",
            "target": target,
            "inputs": inputs,
            "policy": dict(self.policy),
            "state_before": state_before,
            "verdict": verdict,
            "state_after": state_after,
            "actuation": actuation,
        }
        append_decision(self.log_path, event)
        self.state = state_after
        return event

    def run(self, max_ticks: int | None = None) -> int:
        n = 0
        while not self._stop.is_set():
            try:
                self.tick()
            except AutoscaleError:
                raise
            except Exception as e:  # noqa: BLE001 — a flaky store read
                # must not kill the daemon; the next tick re-reads
                append_decision(self.log_path, {
                    "schema": AUTOSCALE_SCHEMA, "ts": time.time(),
                    "event": "tick_error", "error": repr(e)[:300]})
            n += 1
            self.ticks_done = n
            if max_ticks is not None and n >= max_ticks:
                break
            self._stop.wait(self.interval_s)
        return n

    def start_background(self) -> None:
        self._thread = threading.Thread(target=self.run,
                                        name="autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


# ---------------------------------------------------------- selfcheck

def _mk_capacity(max_rps: float = 10.0, *, bundle_sha: str = "cafe" * 16,
                 platform: str = "cpu") -> dict:
    return {"schema": CAPACITY_SCHEMA, "kind": "capacity",
            "created_ts": 0.0, "slo_ms": 50.0, "quantile": "p99",
            "max_rps_at_slo": float(max_rps), "saturated": False,
            "rungs": [{"offered_rps": max_rps, "ok": True}],
            "bundle_sha": bundle_sha, "bundle_version": 1,
            "platform": platform}


def _seed_store(store, ts: float, *, requests_total: float,
                replicas: int, target: str = "router-1") -> None:
    samples = [{"name": "estorch_router_requests_total",
                "labels": {"target": target},
                "value": float(requests_total)}]
    for i in range(replicas):
        samples.append({"name": "estorch_router_replica_up",
                        "labels": {"target": target,
                                   "replica": f"r{i}"},
                        "value": 1.0})
        samples.append({"name": "estorch_router_replica_queue_depth",
                        "labels": {"target": target,
                                   "replica": f"r{i}"},
                        "value": 0.0})
    store.append(samples, ts=ts)


def selfcheck() -> int:
    """The policy + log + refusal contract against a synthetic store:
    demand scale-up, cooldown suppression, burn-rate step, sustained
    low-watermark scale-down, bit-exact replay, tamper detection, and
    the mismatched-capacity refusal naming both sides."""
    import tempfile

    problems: list[str] = []
    with tempfile.TemporaryDirectory() as td:
        store = SeriesStore(os.path.join(td, "store"))
        t0 = 1_000_000.0
        _seed_store(store, t0, requests_total=0.0, replicas=2)
        _seed_store(store, t0 + 10, requests_total=300.0, replicas=2)
        cap_path = os.path.join(td, "capacity.json")
        with open(cap_path, "w") as f:
            json.dump(_mk_capacity(10.0), f)
        calls: list = []

        def fake_actuate(n, reason):
            calls.append((n, reason))
            return {"ok": True, "desired": n}

        policy = {"min_replicas": 2, "max_replicas": 6,
                  "headroom": 1.3, "window_s": 10.0,
                  "up_cooldown_s": 5.0, "down_cooldown_s": 5.0,
                  "low_watermark": 0.5, "low_hold_s": 4.0,
                  "burn_rules": ["p99-slo"]}
        az = Autoscaler(os.path.join(td, "store"), capacity=cap_path,
                        actuate=fake_actuate,
                        fleet_identity={"bundle_sha": "cafe" * 16,
                                        "platform": "cpu",
                                        "bundle": "/b"},
                        policy=policy)
        # demand up: 30 rps over the window, 10 rps/replica capacity,
        # headroom 1.3 → ceil(3.9) = 4
        ev = az.tick(now=t0 + 10)
        if (ev["verdict"]["action"], ev["verdict"]["desired"]) \
                != ("up", 4):
            problems.append(f"demand up: {ev['verdict']}")
        if calls != [(4, "demand")]:
            problems.append(f"actuation: {calls}")
        # cooldown: one second later demand spikes further (40 rps →
        # target 6) but the up-cooldown suppresses the step
        _seed_store(store, t0 + 11, requests_total=700.0, replicas=2)
        ev = az.tick(now=t0 + 11)
        if (ev["verdict"]["action"], ev["verdict"]["reason"]) \
                != ("hold", "up_cooldown"):
            problems.append(f"cooldown: {ev['verdict']}")
        # burn-rate step: demand satisfied (15 rps at 4 replicas) but
        # the SLO alert fires → +1 once the cooldown has passed
        _seed_store(store, t0 + 20, requests_total=1000.0, replicas=4)
        _seed_store(store, t0 + 25, requests_total=1150.0, replicas=4)
        with open(os.path.join(td, "store", LEDGER_FILENAME), "a") as f:
            f.write(json.dumps({"ts": t0 + 24, "event": "firing",
                                "rule": "p99-slo",
                                "target": "router-1"}) + "\n")
        ev = az.tick(now=t0 + 25)
        if (ev["verdict"]["action"], ev["verdict"]["desired"],
                ev["verdict"]["reason"]) != ("up", 5, "burn:p99-slo"):
            problems.append(f"burn step: {ev['verdict']}")
        # resolved alert + sustained low utilization → arm, hold, then
        # step down one replica per down-cooldown window
        with open(os.path.join(td, "store", LEDGER_FILENAME), "a") as f:
            f.write(json.dumps({"ts": t0 + 26, "event": "resolved",
                                "rule": "p99-slo",
                                "target": "router-1"}) + "\n")
        base = 1150.0
        verdicts = []
        for dt in (30.0, 32.0, 35.0, 41.0):
            base += 10.0  # trickle traffic: utilization far below 0.5
            _seed_store(store, t0 + dt, requests_total=base, replicas=5)
            verdicts.append(az.tick(now=t0 + dt)["verdict"])
        shape = [(v["action"], v["reason"]) for v in verdicts]
        if shape != [("hold", "low_watermark_arming"),
                     ("hold", "low_watermark_holding"),
                     ("down", "low_watermark"),
                     ("down", "low_watermark")] \
                or verdicts[-1]["desired"] != 3:
            problems.append(f"low watermark: {verdicts}")
        # bit-exact replay of everything logged above
        rep = replay(az.log_path)
        if not rep["ok"] or rep["decisions"] != 7:
            problems.append(f"replay: {rep}")
        # tamper detection: flip one verdict, replay must flag it
        tampered = os.path.join(td, "tampered.jsonl")
        rows = [json.loads(ln) for ln in open(az.log_path)]
        rows[0]["verdict"]["desired"] = 99
        with open(tampered, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        if replay(tampered)["ok"]:
            problems.append("tampered log replayed clean")
        # the refusal names both sides
        try:
            Autoscaler(os.path.join(td, "store"), capacity=cap_path,
                       actuate=fake_actuate,
                       fleet_identity={"bundle_sha": "dead" * 16,
                                       "platform": "cpu",
                                       "bundle": "/other"})
            problems.append("mismatched capacity model accepted")
        except AutoscaleError as e:
            if "cafecafecafe" not in str(e) or "deaddeaddead" not in str(e):
                problems.append(f"refusal names neither side: {e}")
        # junk artifacts are refused
        if not validate_capacity({"schema": 99}):
            problems.append("junk capacity validated")
        if not validate_capacity(_mk_capacity(10.0)
                                 | {"max_rps_at_slo": None}):
            problems.append("saturated capacity validated")
    for p in problems:
        print(f"FAIL: {p}")
    print(json.dumps({"selfcheck": "autoscale",
                      "ok": not problems,
                      "problems": problems}))
    return 0 if not problems else 1


# ------------------------------------------------------------------ CLI

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs autoscale",
        description="autoscaler daemon: collector store + capacity "
                    "model -> fleet POST /scale "
                    "(docs/serving.md, 'Autoscaling')")
    p.add_argument("--store", metavar="DIR",
                   help="collector store root (obs/agg/store.py)")
    p.add_argument("--fleet-admin", metavar="HOST:PORT",
                   help="the fleet router's admin address "
                        "(POST /scale)")
    p.add_argument("--capacity", metavar="PATH",
                   help="capacity.json from loadgen --capacity-sweep "
                        "--out")
    p.add_argument("--target", default=None,
                   help="router target label in the store (default: "
                        "auto-discover; ambiguity is an error)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between control cycles")
    p.add_argument("--ticks", type=int, default=None,
                   help="stop after N cycles (default: run forever)")
    p.add_argument("--once", action="store_true",
                   help="one cycle, print the decision event, exit")
    p.add_argument("--dry-run", action="store_true",
                   help="decide + log but never actuate")
    p.add_argument("--min", type=int, default=None, dest="min_replicas")
    p.add_argument("--max", type=int, default=None, dest="max_replicas")
    p.add_argument("--headroom", type=float, default=None)
    p.add_argument("--window", type=float, default=None, dest="window_s")
    p.add_argument("--slo-ms", type=float, default=None, dest="slo_ms")
    p.add_argument("--up-cooldown", type=float, default=None,
                   dest="up_cooldown_s")
    p.add_argument("--down-cooldown", type=float, default=None,
                   dest="down_cooldown_s")
    p.add_argument("--low-watermark", type=float, default=None,
                   dest="low_watermark")
    p.add_argument("--low-hold", type=float, default=None,
                   dest="low_hold_s")
    p.add_argument("--burn-rule", action="append", default=None,
                   metavar="NAME", dest="burn_rules",
                   help="alert rule name treated as a burn-rate breach "
                        "(repeatable)")
    p.add_argument("--decision-log", default=None, metavar="PATH",
                   help=f"append-only decision log (default: "
                        f"<store>/{DECISIONS_FILENAME})")
    p.add_argument("--replay", default=None, metavar="LOG",
                   help="re-derive every decision in LOG from its "
                        "recorded inputs and verify bit-exactness")
    p.add_argument("--selfcheck", action="store_true",
                   help="synthetic-store policy/log/refusal gate "
                        "(CI)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.selfcheck:
        return selfcheck()
    if args.replay:
        res = replay(args.replay)
        print(json.dumps(res, indent=1))
        return 0 if res["ok"] else 1
    if not args.store or not args.capacity:
        build_parser().error("--store and --capacity are required "
                             "(or --replay / --selfcheck)")
    if not args.fleet_admin and not args.dry_run:
        build_parser().error("--fleet-admin is required (or --dry-run)")
    policy = {k: v for k, v in vars(args).items()
              if k in POLICY_DEFAULTS and v is not None}
    try:
        az = Autoscaler(args.store, capacity=args.capacity,
                        fleet_admin=args.fleet_admin,
                        target=args.target, policy=policy,
                        interval_s=args.interval,
                        log_path=args.decision_log,
                        dry_run=args.dry_run)
    except AutoscaleError as e:
        print(f"autoscale: {e}", file=sys.stderr)
        return 2
    if args.once:
        try:
            ev = az.tick()
        except AutoscaleError as e:
            print(f"autoscale: {e}", file=sys.stderr)
            return 2
        print(json.dumps(ev, indent=1, default=float))
        return 0
    print(json.dumps({"ready": True, "role": "autoscaler",
                      "store": az.store_root, "log": az.log_path,
                      "fleet_admin": args.fleet_admin,
                      "policy": az.policy, "pid": os.getpid()}),
          flush=True)
    try:
        az.run(max_ticks=args.ticks)
    except AutoscaleError as e:
        print(f"autoscale: {e}", file=sys.stderr)
        return 2
    except KeyboardInterrupt:
        print(json.dumps({"autoscale": "interrupted",
                          "ticks": az.ticks_done}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
