"""Run manifest: the immutable facts a regression hunt needs first.

"Which jax? which devices? which commit? which config?" — questions a
run's JSONL cannot answer about itself.  The manifest is one JSON file
written at run start: config, versions, device topology, git sha,
hostname/pid.  ``ES.run_manifest()`` builds it from a live ES (safe:
the backend is already initialized, so reading device attributes cannot
wedge a cold runtime — the reason this module never calls
``jax.devices()`` on its own).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

MANIFEST_SCHEMA = 1


def _git_sha(cwd: str | None = None) -> str | None:
    """Best-effort HEAD sha; None outside a repo / without git.  Bounded:
    a hung VCS helper must not block run startup (esguard R05)."""
    try:
        r = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, timeout=5.0,
            capture_output=True, text=True,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = r.stdout.strip()
    return sha if r.returncode == 0 and sha else None


def collect_manifest(config: dict | None = None,
                     devices=None,
                     extra: dict | None = None) -> dict:
    """Assemble the manifest dict.

    ``devices``: an iterable of jax Device objects (e.g. ``es.mesh.
    devices.flat``) — pass them from a context that already initialized
    the backend; this function will not touch one itself.
    """
    import socket

    man: dict = {
        "schema": MANIFEST_SCHEMA,
        "created_unix": time.time(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "git_sha": _git_sha(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))),
    }
    try:
        import jax

        man["jax"] = jax.__version__
    except Exception:  # manifest must assemble even on a broken install
        man["jax"] = None
    try:
        import numpy as np

        man["numpy"] = np.__version__
    except Exception:
        man["numpy"] = None
    if devices is not None:
        man["devices"] = [
            {"id": int(getattr(d, "id", i)),
             "platform": str(getattr(d, "platform", "?")),
             "kind": str(getattr(d, "device_kind", "?")),
             "process_index": int(getattr(d, "process_index", 0))}
            for i, d in enumerate(devices)
        ]
    if config is not None:
        man["config"] = config
    if extra:
        man.update(extra)
    return man


def write_manifest(path: str, manifest: dict) -> str:
    """Atomic write (tmp + rename); returns the absolute path."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, default=float)
    os.replace(tmp, path)
    return path


def load_manifest(path: str) -> dict:
    with open(path) as f:
        man = json.load(f)
    if man.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(
            f"manifest schema {man.get('schema')!r} != {MANIFEST_SCHEMA} "
            f"(file: {path})")
    return man
