"""Flight recorder (ring buffer of recent spans/events) + heartbeat file.

The failure mode these exist for: a run wedges — tunnel drop, hung env
pool, deadlocked worker — and the only post-mortem evidence is a
parent's ``timeout after 480s`` line.  The flight recorder keeps the
last N span/event records in memory (dumpable on demand or at crash
handlers); the heartbeat is the *externally visible* half: a tiny JSON
file rewritten atomically at every phase transition, so any supervisor
(bench.py stage parent, examples/tpu_watch.py, doctor.py) can read the
last-known phase + generation + age of a child it cannot otherwise
inspect.

Heartbeat protocol (docs/observability.md):

* writer: serialize ``{"ts", "pid", "phase", "generation", "counters"}``
  to ``path + ".tmp"`` and ``os.replace`` it over ``path`` — readers
  never see a partial file;
* reader: :func:`read_heartbeat` returns the dict plus ``age_s`` (now −
  ts); a missing/corrupt file returns ``None`` — "wedged before the
  first beat" is itself a diagnosis;
* the path travels in the ``ESTORCH_OBS_HEARTBEAT`` environment
  variable, so supervisors enable it for children without touching
  their argv.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

HEARTBEAT_ENV = "ESTORCH_OBS_HEARTBEAT"
# a beat older than this is "stale" for doctor/bench diagnosis purposes;
# generous vs real generation times (seconds) but far below stage timeouts
STALE_AFTER_S = 120.0


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry events (oldest evicted)."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=capacity)

    def add(self, kind: str, name: str, **extra) -> None:
        self._ring.append({"ts": time.time(), "kind": kind, "name": name,
                           **extra})

    def events(self) -> list[dict]:
        """Oldest → newest copy of the ring."""
        return list(self._ring)

    def last(self) -> dict | None:
        return self._ring[-1] if self._ring else None

    def __len__(self) -> int:
        return len(self._ring)

    def dump_jsonl(self, path: str) -> None:
        """Append the ring to a JSONL file (crash-dump / post-mortem).

        Atomic (same tmp + ``os.replace`` contract as :class:`Heartbeat`):
        the append is staged by copying the existing file into ``.tmp``,
        writing the ring after it, then renaming over ``path`` — a crash
        mid-dump leaves either the previous complete file or the new
        complete file, never a truncated JSONL for the metrics sidecar or
        ``obs trace --events`` to choke on."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            if os.path.exists(path):  # single-writer file: no TOCTOU race
                with open(path) as old:
                    prev = old.read()
                if prev and not prev.endswith("\n"):
                    # a pre-atomic-era torn tail is one lost partial
                    # event: DROP it — newline-terminating it would move
                    # the malformed line mid-file, where tolerant readers
                    # rightly treat it as corruption, not a crash artifact
                    cut = prev.rfind("\n")
                    prev = prev[:cut + 1] if cut >= 0 else ""
                f.write(prev)
            for ev in self._ring:
                f.write(json.dumps(ev, default=float) + "\n")
        os.replace(tmp, path)


class Heartbeat:
    """Atomic last-known-state file for external liveness monitoring.

    Thread-safe: the serving stack beats from two threads (the batcher's
    phase entries and the idle-period beater), and both write through the
    same ``.tmp`` staging file — unserialized, a reader could replace-in
    a half-written payload and a watchdog would misread a healthy process
    as corrupt/stale."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)

    def beat(self, phase: str, generation: int,
             counters: dict | None = None,
             hists: dict | None = None) -> None:
        payload = {
            "ts": time.time(),
            "pid": os.getpid(),
            "phase": phase,
            "generation": int(generation),
        }
        if counters:
            payload["counters"] = counters
        if hists:
            # histogram snapshots (obs/hist.py to_dict shape) ride the
            # beat so the supervisor can fold a dead child's latency
            # DISTRIBUTIONS into counters.json, not just its sums
            payload["hists"] = hists
        tmp = self.path + ".tmp"
        with self._lock:
            with open(tmp, "w") as f:
                json.dump(payload, f, default=float)
            os.replace(tmp, self.path)


def read_heartbeat(path: str) -> dict | None:
    """Heartbeat dict + ``age_s``, or None when absent/unreadable.

    None is a finding, not an error: the child either never constructed
    telemetry (wedged in import/init) or was not heartbeat-enabled.
    """
    try:
        with open(path) as f:
            hb = json.load(f)
        hb["age_s"] = max(0.0, time.time() - float(hb["ts"]))
        return hb
    except (OSError, ValueError, KeyError, TypeError):
        return None


def describe_heartbeat(path: str) -> str:
    """One diagnostic clause for failure lines: last phase + gen + age."""
    hb = read_heartbeat(path)
    if hb is None:
        return "no heartbeat written — wedged before the first phase?"
    return (f"last phase={hb.get('phase', '?')} "
            f"gen={hb.get('generation', '?')} "
            f"heartbeat {hb['age_s']:.0f}s ago")
