"""Device-trace hooks (migrated from ``utils/profiler.py``, which remains
a re-export shim).

Span telemetry (obs/spans.py) answers "which phase got slower" for free
on every run; these helpers are the heavyweight next step when a phase
needs opening up:

- ``trace(logdir)``: context manager around ``jax.profiler`` producing a
  Perfetto/XPlane trace of the compiled generation programs;
- ``timed_generations(es, n)``: per-generation wall/device split using
  ``block_until_ready`` fences — the cheap always-available option;
- ``annotate(name)`` via ``jax.profiler.TraceAnnotation`` for host-side
  phases (novelty k-NN, archive ops) so they show up inside device
  traces.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace of everything inside the with-block."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Host-phase annotation visible in device traces (no-op off-trace)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def timed_generations(es, n: int = 5, warmup: int = 1) -> dict:
    """Run ``n`` timed generations; returns aggregate timing stats.

    Forces AOT compile (via train's first call) and a ``warmup``
    generation first so results measure steady-state execution only.
    The wall clock is fenced: ``es.train`` blocks on the updated
    parameters every generation, so the delta below measures executed
    compute, not async dispatch (esguard R07 contract).
    """
    es.train(warmup, verbose=False)
    t0 = time.perf_counter()
    es.train(n, verbose=False)
    wall = time.perf_counter() - t0
    recs = es.history[-n:]
    steps = sum(r["env_steps"] for r in recs)
    return {
        "generations": n,
        "wall_s": wall,
        "gen_per_sec": n / wall,
        "env_steps": steps,
        "env_steps_per_sec": steps / wall,
        "mean_gen_wall_s": wall / n,
        "compile_time_s": es.compile_time_s,
    }
