"""Analytic FLOPs/bytes cost model per engine phase.

Wall-clock spans (PR 2) say *where* the time went; this module says what
that time *bought*: an analytic floating-op and byte-traffic estimate
per phase, derived from the run configuration alone (population, policy
matmul shapes, noise representation), so ``obs profile`` can turn
per-phase seconds into achieved FLOP/s and bytes/s against a platform
roofline — the accounting "Evolution Strategies at the Hyperscale"
(PAPERS.md) frames ES throughput in.

The model is deliberately COARSE and says so: it counts the dominant
terms only (policy matmuls for the forward, table-row traffic for the
noise pathways) and ignores elementwise epilogues, env dynamics, and
collectives.  Its job is attribution to the right order of magnitude —
the compile-time cross-check against XLA's own ``cost_analysis()``
(:func:`compiled_cost_facts`, recorded in the compile ledger) is what
keeps it honest: ``obs profile`` reports the model/XLA ratio whenever
both are available.

Deliberately stdlib-only and importable without jax (the ``obs
profile`` CLI must diagnose runs from a wedged-runtime host, like every
other obs surface).

Phase mapping (docs/observability.md span taxonomy):

* ``sample`` — perturbation construction: table-row reads + scaled add;
* ``eval``  — policy forwards over every member env-step;
* ``update``— the rank-weighted noise reduction;
* ``device`` (fused path) — one XLA program containing all three: its
  cost is their sum; ``dispatch``/``host_sync`` carry no modeled cost.
"""

from __future__ import annotations

COST_MODEL_SCHEMA = 1

# phases whose cost is the per-generation sum of every modeled phase —
# the fused device program cannot be split host-side (spans taxonomy)
FUSED_PHASES = ("device",)
MODELED_PHASES = ("sample", "eval", "update")


def matmul_flops(matmul_shapes) -> int:
    """2·Σ(m·n) over the policy's 2-D kernels — multiply-add per forward."""
    return 2 * sum(int(m) * int(n) for m, n in matmul_shapes)


def lowrank_noise_dim(matmul_shapes, rank: int, param_dim: int) -> int:
    """Packed (A‖B‖bias) factor length (ops/lowrank.py): every 2-D kernel
    contributes (m+n)·r, every non-kernel param stays dense."""
    kernel_params = sum(int(m) * int(n) for m, n in matmul_shapes)
    factors = sum((int(m) + int(n)) * rank for m, n in matmul_shapes)
    return factors + (param_dim - kernel_params)


def generation_cost(*, population: int, matmul_shapes, param_dim: int,
                    horizon: int | None = None,
                    episodes_per_member: int = 1,
                    mirrored: bool = True,
                    low_rank: int = 0,
                    dtype_bytes: int = 4,
                    noise: str = "table",
                    n_devices: int = 1,
                    model_shards: int = 1) -> dict:
    """Per-phase FLOPs/bytes for ONE generation of this configuration.

    ``horizon`` may be None (host agents own their rollout length); the
    ``eval`` entry is then omitted and consumers derive eval cost from
    the per-record ``env_steps`` × ``flops_per_env_step`` instead —
    which is also what ``obs profile`` does even when horizon is known,
    so early-terminating envs (done masks) are charged only for the
    steps they actually ran.

    ``noise="program"`` (the param-sharded engine's in-program ε,
    parallel/sharded.py) changes the BYTE model: no table rows are ever
    read — ε is generated in-registers — so sample/update traffic is the
    param-sized center/accumulator only.  RNG hashing FLOPs are not
    modeled (coarse-model contract; they scale like the scaled-add the
    model does count).

    ``n_devices``/``model_shards`` record the mesh and add a
    ``sharding`` block with PER-DEVICE unit costs: an env-step's forward
    is split over the ``model`` axis, so a per-chip MFU that divides
    whole-program FLOPs by chip seconds must use
    ``per_device_flops_per_env_step × total steps``, not pretend each
    chip ran every step's full forward — the "per-shard attribution"
    that keeps sharded MFU honest.
    """
    matmul_shapes = [tuple(int(d) for d in s) for s in matmul_shapes]
    population = int(population)
    param_dim = int(param_dim)
    n_devices = max(int(n_devices), 1)
    model_shards = max(int(model_shards), 1)
    fwd = matmul_flops(matmul_shapes)
    if low_rank:
        noise_dim = lowrank_noise_dim(matmul_shapes, int(low_rank), param_dim)
        # factored noise term per step: 2·Σ(m+n)·r instead of the dense 2·m·n
        fwd_step = fwd + 2 * sum((m + n) * int(low_rank)
                                 for m, n in matmul_shapes)
    else:
        noise_dim = param_dim
        fwd_step = fwd
    # distinct noise rows per generation: one per antithetic PAIR when
    # mirrored (both members share the row), one per member otherwise
    rows = population // 2 if mirrored else population
    # table rows are HBM traffic; in-program rows are RNG output and
    # never touch memory (streamed straight into the scaled-add/FMA)
    row_read_bytes = 0 if noise == "program" else rows * noise_dim * dtype_bytes
    per_gen = {
        # theta = params + sigma·sign·eps: one scaled add over the noise
        # vector per member; bytes = the noise rows (table mode only)
        # plus the center read per member
        "sample": {
            "flops": 2 * population * noise_dim,
            "bytes": row_read_bytes + population * param_dim * dtype_bytes,
        },
        # rank-weighted noise sum: one FMA per noise element per row;
        # bytes = re-reading every row (table mode) plus the param-sized
        # accumulator
        "update": {
            "flops": 2 * rows * noise_dim,
            "bytes": row_read_bytes + param_dim * dtype_bytes,
        },
    }
    out = {
        "schema": COST_MODEL_SCHEMA,
        # forward FLOPs per member env-step — the eval phase's unit cost
        "flops_per_env_step": fwd_step,
        # per-step traffic ≈ the member's weights through the MXU/ALU
        # (GEMV regime; batched rollouts amortize this, so treat it as an
        # upper bound on eval bytes)
        "bytes_per_env_step": param_dim * dtype_bytes,
        "per_generation": per_gen,
        "population": population,
        "param_dim": param_dim,
        "noise_dim": noise_dim,
        "mirrored": bool(mirrored),
        "low_rank": int(low_rank),
        "episodes_per_member": int(episodes_per_member),
        "dtype_bytes": int(dtype_bytes),
        "noise": str(noise),
        "matmul_shapes": [list(s) for s in matmul_shapes],
    }
    if n_devices > 1 or model_shards > 1:
        out["sharding"] = {
            "n_devices": n_devices,
            "model_shards": model_shards,
            "pop_shards": n_devices // model_shards,
            # one env-step's forward work per chip (split over model)
            "per_device_flops_per_env_step": fwd_step / model_shards,
            # resident center bytes per chip — the replicated-vs-sharded
            # memory argument in one number (docs/sharding.md)
            "per_device_param_bytes": param_dim * dtype_bytes / model_shards,
        }
    if horizon is not None:
        steps = population * int(horizon) * int(episodes_per_member)
        out["env_steps_per_generation"] = steps
        per_gen["eval"] = {
            "flops": steps * fwd_step,
            "bytes": steps * param_dim * dtype_bytes,
        }
    return out


def phase_cost_for(model: dict, phase: str, *, env_steps: int,
                   n_generations: int) -> dict | None:
    """Modeled {flops, bytes} for ``phase`` over a whole run, or None
    when the model has nothing to say about it (dispatch, host_sync,
    nested children).  ``env_steps`` is the run total (honest for
    early-terminating envs); fused phases get the sum of every modeled
    phase."""
    if not isinstance(model, dict) or "per_generation" not in model:
        return None
    per_gen = model["per_generation"]

    def eval_cost() -> dict:
        return {
            "flops": env_steps * model.get("flops_per_env_step", 0),
            "bytes": env_steps * model.get("bytes_per_env_step", 0),
        }

    def scaled(name: str) -> dict | None:
        ent = per_gen.get(name)
        if not isinstance(ent, dict):
            return None
        return {"flops": ent.get("flops", 0) * n_generations,
                "bytes": ent.get("bytes", 0) * n_generations}

    if phase == "eval":
        return eval_cost()
    if phase in ("sample", "update"):
        return scaled(phase)
    if phase in FUSED_PHASES:
        total = eval_cost()
        for name in ("sample", "update"):
            ent = scaled(name)
            if ent:
                total["flops"] += ent["flops"]
                total["bytes"] += ent["bytes"]
        return total
    return None


def _probe_cost_analysis(compiled) -> dict:
    """``compiled.cost_analysis()`` facts, or {} when this jax version
    does not provide the (best-effort) API — the fall-through probe
    shape: the handler's pass dispatches to the empty-dict fallback."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        out: dict = {}
        if isinstance(ca, dict):
            flops = ca.get("flops")
            if isinstance(flops, (int, float)) and flops > 0:
                out["xla_flops"] = float(flops)
            acc = ca.get("bytes accessed")
            if isinstance(acc, (int, float)) and acc > 0:
                out["xla_bytes_accessed"] = float(acc)
        return out
    except Exception:  # noqa: BLE001 — absent/changed best-effort API
        pass
    return {}


def _probe_memory_analysis(compiled) -> dict:
    """``compiled.memory_analysis()`` peak-bytes fact, same probe shape."""
    try:
        ma = compiled.memory_analysis()
        peak = sum(
            float(getattr(ma, attr, 0) or 0)
            for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                         "temp_size_in_bytes"))
        return {"peak_bytes": peak} if peak > 0 else {}
    except Exception:  # noqa: BLE001 — absent/changed best-effort API
        pass
    return {}


def compiled_cost_facts(compiled) -> dict:
    """FLOPs/bytes/peak-memory facts from a jax ``Compiled`` object, for
    the compile ledger — empty dict when this jax version exposes
    neither ``cost_analysis()`` nor ``memory_analysis()`` (both are
    best-effort APIs; the analytic model stands alone then).

    Duck-typed on purpose: no jax import, so the obs package contract
    (importable from a wedged host) holds.
    """
    out: dict = {}
    out.update(_probe_cost_analysis(compiled))
    out.update(_probe_memory_analysis(compiled))
    return out
