"""Platform rooflines: the denominators that make achieved rates honest.

``obs profile`` divides per-phase achieved FLOP/s and bytes/s by a
platform peak.  On TPU the peak is a datasheet fact (v5e bf16 MXU peak,
HBM bandwidth — the same 197 TFLOP/s denominator bench.py has always
used for ``mfu``).  On CPU there is no such number worth quoting: the
"peak" of a loaded shared-core host is whatever it can actually do
today — so the CPU roofline is MEASURED, not quoted: a short in-process
GEMM (numpy → BLAS, the best compute this host offers python) and a
large memcpy (stream bandwidth).  Every CPU-derived utilization is
tagged ``cpu_calibrated`` so nobody mistakes "fraction of this host's
measured GEMM rate" for an MFU against accelerator silicon.

Deliberately jax-free (numpy + stdlib): bench.py's driver and the
``obs profile`` CLI both need a roofline on hosts where the device
runtime is wedged.
"""

from __future__ import annotations

import time

import numpy as np

# TPU v5e per-chip datasheet peaks: bf16 MXU FLOP/s (the bench.py
# denominator since round 1) and HBM bandwidth
V5E_BF16_PEAK_FLOPS = 197e12
V5E_HBM_BYTES_PER_S = 819e9

TPU_V5E_ROOFLINE = {
    "platform": "tpu",
    "basis": "tpu_v5e_bf16_peak",
    "peak_flops_per_s": V5E_BF16_PEAK_FLOPS,
    "peak_bytes_per_s": V5E_HBM_BYTES_PER_S,
}

_CPU_CACHE: dict | None = None


def measure_cpu_roofline(budget_s: float = 0.25, gemm_n: int = 384,
                         copy_mb: int = 32) -> dict:
    """Measured CPU roofline: best-of-repeats GEMM FLOP/s + memcpy bytes/s.

    Best-of (not median): the roofline is the *ceiling* this host can
    reach, and on a loaded shared core every slow repeat is interference,
    not capability.  ``budget_s`` bounds each of the two measurements.
    """
    n = int(gemm_n)
    a = np.random.default_rng(0).standard_normal((n, n)).astype(np.float32)
    b = np.random.default_rng(1).standard_normal((n, n)).astype(np.float32)
    a @ b  # warm-up: BLAS thread pool + page faults outside the clock
    flops_per_mm = 2.0 * n * n * n
    best_flops = 0.0
    deadline = time.perf_counter() + float(budget_s)
    while time.perf_counter() < deadline:
        t0 = time.perf_counter()
        a @ b
        dt = time.perf_counter() - t0
        if dt > 0:
            best_flops = max(best_flops, flops_per_mm / dt)

    src = np.zeros(int(copy_mb) * 2**20 // 4, np.float32)
    dst = np.empty_like(src)
    np.copyto(dst, src)  # warm-up
    moved = 2.0 * src.nbytes  # one read + one write per copy
    best_bw = 0.0
    deadline = time.perf_counter() + float(budget_s)
    while time.perf_counter() < deadline:
        t0 = time.perf_counter()
        np.copyto(dst, src)
        dt = time.perf_counter() - t0
        if dt > 0:
            best_bw = max(best_bw, moved / dt)
    return {
        "platform": "cpu",
        "basis": "cpu_calibrated",
        "peak_flops_per_s": best_flops,
        "peak_bytes_per_s": best_bw,
        "gemm_n": n,
        "copy_mb": int(copy_mb),
    }


def platform_roofline(platform: str, measure: bool = True) -> dict:
    """The roofline for ``platform``: datasheet on TPU, measured on CPU
    (cached per process — the calibration GEMM should run once, not per
    phase).  ``measure=False`` on CPU returns None-peaks with the
    ``cpu_calibrated`` basis, for callers that only want the tag.

    Any OTHER platform (gpu, …) gets None-peaks and no basis: the host
    GEMM calibration measures this host's CPU, and dividing an
    accelerator's rate by it would produce exactly the dishonest
    cross-silicon number the basis tag exists to prevent — rates-only
    reporting is the honest answer until that platform gets its own
    denominator."""
    global _CPU_CACHE
    if platform == "tpu":
        return dict(TPU_V5E_ROOFLINE)
    if platform != "cpu":
        return {"platform": str(platform), "basis": None,
                "peak_flops_per_s": None, "peak_bytes_per_s": None}
    if not measure:
        return {"platform": "cpu", "basis": "cpu_calibrated",
                "peak_flops_per_s": None, "peak_bytes_per_s": None}
    if _CPU_CACHE is None:
        _CPU_CACHE = measure_cpu_roofline()
    return dict(_CPU_CACHE)
