"""estorch_tpu.obs.profile — per-phase performance attribution.

The accounting layer over the span/counters hub (docs/observability.md
"Profiling"): turn wall-clock phase spans into achieved FLOP/s and
bytes/s against a platform roofline, keep per-program compile facts in
a structured ledger, and report MFU that is real on TPU and honestly
``cpu_calibrated`` off-chip.

- :mod:`costmodel` — analytic FLOPs/bytes per phase from the run config;
- :mod:`roofline`  — v5e datasheet peaks / measured CPU calibration;
- :mod:`ledger`    — compile events riding JSONL, Prometheus, Perfetto;
- :mod:`report`    — the ``obs profile`` CLI body + selfcheck.
"""

from .costmodel import (FUSED_PHASES, MODELED_PHASES, compiled_cost_facts,
                        generation_cost, phase_cost_for)
from .ledger import CompileLedger, collect_compile_events, ledger_counters
from .report import (find_cost_model, format_profile, profile_records,
                     selfcheck)
from .roofline import (TPU_V5E_ROOFLINE, measure_cpu_roofline,
                       platform_roofline)

__all__ = [
    "FUSED_PHASES",
    "MODELED_PHASES",
    "CompileLedger",
    "TPU_V5E_ROOFLINE",
    "collect_compile_events",
    "compiled_cost_facts",
    "find_cost_model",
    "format_profile",
    "generation_cost",
    "ledger_counters",
    "measure_cpu_roofline",
    "phase_cost_for",
    "platform_roofline",
    "profile_records",
    "selfcheck",
]
