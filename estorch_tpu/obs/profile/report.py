"""``obs profile`` — per-phase performance attribution over a run JSONL.

``obs summarize`` says where the time went; this module says what that
time *bought*: each top-level phase's seconds are joined with the
analytic cost model the run recorded (``record["cost_model"]``, written
by ``ES`` at generation 0) to produce achieved FLOP/s, bytes/s, and
arithmetic intensity, each stated against a platform roofline
(:mod:`roofline` — v5e datasheet peaks on TPU, a measured-GEMM
calibration on CPU so off-chip numbers are honest rather than null).
The compile ledger (``record["compile_events"]``) rides along: per-
program compile seconds, XLA's own cost estimates, and the model/XLA
FLOPs ratio — the cross-check that keeps the analytic model honest.

Tolerance contract (matches summarize/trace): phase-less records, a
truncated tail, or a run with zero compile events degrade to a noted,
partial report — never a crash; post-mortem inputs are exactly the runs
that died mid-write.
"""

from __future__ import annotations

import math

from . import costmodel
from .ledger import collect_compile_events

PROFILE_SCHEMA = 1

# phases that are pure host-side bookkeeping: no modeled cost, and their
# absence from the modeled set is by design, not a gap
UNMODELED_PHASES = ("dispatch", "host_sync", "record")


def _dedup_replays(records: list[dict]) -> list[dict]:
    """Keep the LAST occurrence per generation (supervisor replays), the
    same rule summarize/regress apply."""
    gens = [r.get("generation") for r in records if isinstance(r, dict)]
    records = [r for r in records if isinstance(r, dict)]
    if len(set(g for g in gens if g is not None)) == sum(
            1 for g in gens if g is not None):
        return records
    last = {g: i for i, g in enumerate(gens) if g is not None}
    return [r for i, r in enumerate(records)
            if gens[i] is None or last[gens[i]] == i]


def find_cost_model(records: list[dict]) -> dict | None:
    """The run's recorded analytic cost model (first record carrying
    one — ES writes it at generation 0)."""
    for r in records:
        if isinstance(r, dict) and isinstance(r.get("cost_model"), dict):
            return r["cost_model"]
    return None


def profile_records(records: list[dict], roofline: dict,
                    cost_model: dict | None = None) -> dict:
    """Build the profile dict the CLI renders (see module docstring).

    ``roofline``: a :func:`roofline.platform_roofline` dict; its peaks
    may be None (un-calibrated), in which case utilizations are omitted
    and the report is rates-only.
    """
    notes: list[str] = []
    records = _dedup_replays(records)
    if not records:
        return {"schema": PROFILE_SCHEMA, "generations": 0,
                "notes": ["no records"]}
    model = cost_model or find_cost_model(records)
    if model is None:
        notes.append("no cost_model in the run records — time shares "
                     "only (runs from before the profile layer, or a "
                     "hand-built JSONL)")

    n_gens = len(records)
    env_steps = sum(int(r.get("env_steps", 0) or 0) for r in records)
    wall = sum(float(r.get("wall_time_s", 0.0) or 0.0) for r in records)

    top: dict[str, float] = {}
    for r in records:
        for name, dur in (r.get("phases") or {}).items():
            if isinstance(dur, (int, float)) and "/" not in name:
                top[name] = top.get(name, 0.0) + float(dur)
    if not top:
        notes.append("no phase spans recorded (telemetry disabled?) — "
                     "nothing to attribute")
    span_total = sum(top.values())

    peak_f = roofline.get("peak_flops_per_s")
    peak_b = roofline.get("peak_bytes_per_s")
    ridge = (peak_f / peak_b) if peak_f and peak_b else None

    phases: dict[str, dict] = {}
    modeled_flops_total = 0.0
    for name, sec in sorted(top.items(), key=lambda kv: -kv[1]):
        row: dict = {
            "seconds": round(sec, 4),
            "share": round(sec / span_total, 4) if span_total else 0.0,
        }
        cost = costmodel.phase_cost_for(
            model, name, env_steps=env_steps, n_generations=n_gens
        ) if model else None
        if cost is not None and sec > 0:
            flops, nbytes = float(cost["flops"]), float(cost["bytes"])
            modeled_flops_total += flops
            row["modeled_flops"] = flops
            row["flops_per_s"] = round(flops / sec, 1)
            row["bytes_per_s"] = round(nbytes / sec, 1)
            row["arith_intensity"] = (round(flops / nbytes, 3)
                                      if nbytes else None)
            # mfu/bw_util stay unrounded: the selfcheck's known-FLOPs
            # gate compares them exactly (format_profile rounds for
            # display)
            if peak_f:
                row["mfu"] = flops / sec / peak_f
            if peak_b:
                row["bw_util"] = nbytes / sec / peak_b
            if ridge is not None and row["arith_intensity"] is not None:
                row["bound"] = ("compute"
                                if row["arith_intensity"] >= ridge
                                else "memory")
        phases[name] = row

    run: dict = {}
    if model and wall > 0 and modeled_flops_total > 0:
        run = {"modeled_flops": modeled_flops_total,
               "flops_per_s": round(modeled_flops_total / wall, 1)}
        if peak_f:
            run["mfu"] = modeled_flops_total / wall / peak_f

    # ---- compile ledger -------------------------------------------------
    entries = collect_compile_events(records)
    compile_block: dict = {"n_events": len(entries)}
    if entries:
        compile_block["total_compile_s"] = round(
            sum(float(e.get("compile_s", 0.0) or 0.0) for e in entries), 4)
        compile_block["programs"] = [
            {k: e[k] for k in ("program", "compile_s", "generation",
                               "xla_flops", "xla_bytes_accessed",
                               "peak_bytes", "first_call") if k in e}
            for e in entries
        ]
        peaks = [e["peak_bytes"] for e in entries
                 if isinstance(e.get("peak_bytes"), (int, float))]
        if peaks:
            compile_block["peak_device_bytes"] = max(peaks)
        # model/XLA cross-check: the fused generation program's XLA FLOPs
        # estimate vs the analytic model's per-generation total
        if model:
            xla = next((e.get("xla_flops") for e in entries
                        if e.get("program") == "generation_step"
                        and isinstance(e.get("xla_flops"), (int, float))),
                       None)
            per_gen = costmodel.phase_cost_for(
                model, "device", env_steps=env_steps // max(1, n_gens),
                n_generations=1)
            if xla and per_gen and per_gen["flops"] > 0:
                compile_block["model_vs_xla_flops_ratio"] = round(
                    per_gen["flops"] / float(xla), 3)
    else:
        notes.append("no compile events in the run (host backend, "
                     "telemetry disabled, or a pre-ledger run)")

    out = {
        "schema": PROFILE_SCHEMA,
        "generations": n_gens,
        "wall_time_s": round(wall, 3),
        "env_steps": env_steps,
        "platform": roofline.get("platform"),
        "basis": roofline.get("basis"),
        "roofline": {
            "peak_flops_per_s": peak_f,
            "peak_bytes_per_s": peak_b,
            **({"ridge_flops_per_byte": round(ridge, 3)} if ridge else {}),
        },
        "has_cost_model": model is not None,
        "phases": phases,
        "compile": compile_block,
        "notes": notes,
    }
    if run:
        out["run"] = run
    return out


def _rate(v: float | None, unit: str) -> str:
    if v is None or not math.isfinite(v):
        return "n/a"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f} {suffix}{unit}"
    return f"{v:.1f} {unit}"


def format_profile(p: dict) -> str:
    """Human rendering of :func:`profile_records`'s dict."""
    if not p.get("generations"):
        return "\n".join(["no records"] + [f"note: {n}"
                                           for n in p.get("notes", [])])
    lines = [
        f"generations      {p['generations']}",
        f"wall time        {p['wall_time_s']:.3f}s",
        f"env steps        {p['env_steps']:,}",
        f"platform         {p.get('platform')} (basis: {p.get('basis')})",
    ]
    roof = p.get("roofline") or {}
    if roof.get("peak_flops_per_s"):
        lines.append(
            f"roofline         {_rate(roof['peak_flops_per_s'], 'FLOP/s')}"
            f" / {_rate(roof.get('peak_bytes_per_s'), 'B/s')}"
            + (f"  (ridge {roof['ridge_flops_per_byte']} FLOP/B)"
               if roof.get("ridge_flops_per_byte") else ""))
    if p.get("run", {}).get("mfu") is not None:
        lines.append(f"run MFU          {p['run']['mfu']:.4%}  "
                     f"({_rate(p['run']['flops_per_s'], 'FLOP/s')})")
    if p.get("phases"):
        lines.append("phase            share     seconds   achieved")
        for name, row in p["phases"].items():
            ach = ""
            if "flops_per_s" in row:
                ach = _rate(row["flops_per_s"], "FLOP/s")
                if row.get("mfu") is not None:
                    ach += f"  mfu {row['mfu']:.4%}"
                if row.get("bound"):
                    ach += f"  [{row['bound']}-bound"
                    if row.get("arith_intensity") is not None:
                        ach += f", {row['arith_intensity']} FLOP/B"
                    ach += "]"
            lines.append(f"  {name:<14} {row['share']:7.1%}  "
                         f"{row['seconds']:9.3f}s  {ach}")
    c = p.get("compile") or {}
    if c.get("n_events"):
        lines.append(f"compiles         {c['n_events']} program(s), "
                     f"{c.get('total_compile_s', 0)}s total"
                     + (f", peak device bytes "
                        f"{_rate(c['peak_device_bytes'], 'B')}"
                        if c.get("peak_device_bytes") else ""))
        if c.get("model_vs_xla_flops_ratio") is not None:
            lines.append(f"model vs XLA     analytic/XLA FLOPs ratio "
                         f"{c['model_vs_xla_flops_ratio']} "
                         "(the cost model's honesty check)")
    for n in p.get("notes", []):
        lines.append(f"note: {n}")
    return "\n".join(lines)


# ---------------------------------------------------------------------
# selfcheck: the run_lint.sh gate for the attribution layer
# ---------------------------------------------------------------------

def _synth_records(model: dict, n: int = 8, eval_s: float = 1.0,
                   sample_s: float = 0.02, update_s: float = 0.1) -> list:
    import json as _json

    steps = int(model["env_steps_per_generation"])
    recs = []
    for g in range(n):
        wall = sample_s + eval_s + update_s
        rec = {
            "generation": g, "env_steps": steps,
            "env_steps_per_sec": steps / wall, "wall_time_s": wall,
            "reward_mean": 0.0, "reward_max": 0.0, "best_reward": 0.0,
            "phases": {"sample": sample_s, "eval": eval_s,
                       "update": update_s},
        }
        if g == 0:
            rec["cost_model"] = model
            rec["compile_events"] = [
                {"program": "generation_step", "compile_s": 12.5,
                 "generation": 0,
                 "xla_flops": float(model["env_steps_per_generation"]
                                    * model["flops_per_env_step"]),
                 "peak_bytes": 2.5e9},
            ]
        recs.append(_json.loads(_json.dumps(rec)))  # via-JSON: CLI-equal
    return recs


def selfcheck() -> list[str]:
    """Prove the attribution layer computes what it claims ([] = healthy):

    * a synthetic run with known per-step FLOPs and a synthetic roofline
      produces exactly the expected eval-phase MFU;
    * the compile ledger rides the records and round-trips through the
      Prometheus exposition parser;
    * degenerate inputs (phase-less records, no cost model) degrade to a
      noted report, never a crash;
    * a 30% eval-phase slowdown is flagged by the phase-localized
      regress gate naming the ``eval`` phase — and only it;
    * the CPU roofline calibration measures positive peaks.
    """
    from ..export import regress
    from ..export.prometheus import (parse_exposition, render_exposition,
                                     samples_by_name)
    from .ledger import ledger_counters
    from .roofline import measure_cpu_roofline

    problems: list[str] = []
    shapes = [(3, 64), (64, 64), (64, 1)]
    kernels = sum(m * n for m, n in shapes)
    param_dim = kernels + 64 + 64 + 1
    model = costmodel.generation_cost(
        population=4096, matmul_shapes=shapes, param_dim=param_dim,
        horizon=200)
    recs = _synth_records(model)
    roof = {"platform": "synthetic", "basis": "selfcheck",
            "peak_flops_per_s": 1e12, "peak_bytes_per_s": 1e11}
    p = profile_records(recs, roof)
    fwd = 2 * kernels
    want_mfu = (model["env_steps_per_generation"] * fwd) / 1.0 / 1e12
    got = p.get("phases", {}).get("eval", {}).get("mfu")
    if got is None or abs(got - want_mfu) > 1e-12:
        problems.append(f"known-FLOPs eval MFU wrong: got {got}, "
                        f"want {want_mfu}")
    # the model says ES eval is GEMV-regime (intensity ~0.5 FLOP/B):
    # below this roofline's ridge of 10 it must read memory-bound, and
    # against a bandwidth-rich roofline (ridge 0.01) compute-bound —
    # both branches of the classification, not just one
    if p.get("phases", {}).get("eval", {}).get("bound") != "memory":
        problems.append("eval phase (intensity << ridge) not marked "
                        "memory-bound")
    roof_bw = dict(roof, peak_bytes_per_s=1e14)
    p_bw = profile_records(recs, roof_bw)
    if p_bw.get("phases", {}).get("eval", {}).get("bound") != "compute":
        problems.append("eval phase (intensity >> ridge) not marked "
                        "compute-bound")
    if p.get("compile", {}).get("n_events") != 1:
        problems.append("compile ledger entry did not ride the records")
    ratio = p.get("compile", {}).get("model_vs_xla_flops_ratio")
    if ratio is None or not (0.9 <= ratio <= 1.1):
        problems.append(f"model-vs-XLA cross-check ratio off: {ratio}")
    if format_profile(p) == "no records":
        problems.append("format_profile rendered nothing")

    # ledger -> flat registry -> exposition -> parser round trip
    entries = recs[0]["compile_events"]
    folded = ledger_counters(entries)
    body = render_exposition(folded, up=True)
    try:
        vals = samples_by_name(parse_exposition(body))
    except ValueError as e:
        problems.append(f"ledger exposition did not parse: {e}")
        vals = {}
    if vals.get("estorch_compile_s_generation_step") != 12.5:
        problems.append("compile_s did not round-trip the exposition "
                        f"parser: {vals}")

    # degenerate inputs: never a crash, always a note
    bare = [{"generation": g, "env_steps": 10, "env_steps_per_sec": 1.0,
             "wall_time_s": 10.0, "reward_mean": 0, "reward_max": 0,
             "best_reward": 0} for g in range(3)]
    pb = profile_records(bare, roof)
    if not any("no phase spans" in n for n in pb.get("notes", [])):
        problems.append("phase-less records not noted")
    if not any("no cost_model" in n for n in pb.get("notes", [])):
        problems.append("missing cost model not noted")
    if not any("no compile events" in n for n in pb.get("notes", [])):
        problems.append("zero compile events not noted")
    if profile_records([], roof).get("generations") != 0:
        problems.append("empty record list mishandled")

    # phase-localized regression: 30% slower eval must be flagged as
    # eval — and only eval
    slow = _synth_records(model, eval_s=1.3)
    v = regress.compare_phases(slow, recs)
    if v["verdict"] != "regress" or v.get("regressed_phases") != ["eval"]:
        problems.append(f"30% eval slowdown not localized to eval: {v}")
    same = regress.compare_phases(_synth_records(model), recs)
    if same["verdict"] != "pass":
        problems.append(f"identical run flagged by phase gate: {same}")

    cal = measure_cpu_roofline(budget_s=0.05, gemm_n=128, copy_mb=4)
    if not (cal["peak_flops_per_s"] > 0 and cal["peak_bytes_per_s"] > 0):
        problems.append(f"cpu roofline calibration not positive: {cal}")
    if cal["basis"] != "cpu_calibrated":
        problems.append("cpu roofline not tagged cpu_calibrated")
    return problems
