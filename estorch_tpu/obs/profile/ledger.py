"""Compile ledger: per-program compile events as first-class run facts.

Every XLA program an engine builds costs a compile (seconds of wall
time, and — where the jax version exposes ``cost_analysis()`` /
``memory_analysis()`` — XLA's own FLOPs/bytes/peak-memory estimates for
what the program will do per execution).  Until now those facts died as
one ``recompiles`` counter and a flight-recorder event; the ledger keeps
them structured so they ride every export surface:

* the run JSONL — ``Telemetry.take_compile_events()`` flushes entries
  recorded since the last generation record into
  ``record["compile_events"]``;
* Prometheus — :func:`ledger_counters` folds entries into the flat
  registry as ``compile_s_<program>`` / ``compile_peak_bytes_<program>``
  gauges, which the serve server's ``/metrics`` and the sidecar render
  and the validating parser round-trips;
* the Perfetto trace — ``obs trace`` renders each entry as an instant
  marker on a ``compiles`` lane.

Thread-safe (the serving batcher records bucket compiles from its worker
thread while the main thread reads), stdlib-only, jax-free — the facts
arrive duck-typed via :func:`costmodel.compiled_cost_facts`.
"""

from __future__ import annotations

import re
import threading

LEDGER_SCHEMA = 1

# ledger fact -> flat registry prefix (gauges: last-write-wins per
# program; prometheus.is_gauge treats the compile_ prefix as gauge)
_FACT_PREFIX = {
    "compile_s": "compile_s",
    "xla_flops": "compile_xla_flops",
    "xla_bytes_accessed": "compile_xla_bytes",
    "peak_bytes": "compile_peak_bytes",
}

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


class CompileLedger:
    """Append-only record of compile events for one run/process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: list[dict] = []
        self._flushed = 0  # cursor for take_new (run-JSONL riding)

    def record(self, program: str, compile_s: float, generation: int = 0,
               **facts) -> dict:
        entry = {
            "program": str(program),
            "compile_s": round(float(compile_s), 6),
            "generation": int(generation),
        }
        for k, v in facts.items():
            if v is not None:
                entry[k] = v
        with self._lock:
            self._entries.append(entry)
        return entry

    def entries(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._entries]

    def take_new(self) -> list[dict]:
        """Entries recorded since the last call — the per-generation
        flush that lands in ``record["compile_events"]``."""
        with self._lock:
            new = [dict(e) for e in self._entries[self._flushed:]]
            self._flushed = len(self._entries)
        return new

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def ledger_counters(entries: list[dict]) -> dict[str, float]:
    """Fold ledger entries into flat registry names (per-program gauges,
    last entry wins) — the form the Prometheus exposition renders and
    its validating parser round-trips."""
    out: dict[str, float] = {}
    for e in entries:
        if not isinstance(e, dict) or "program" not in e:
            continue
        prog = _NAME_SANITIZE.sub("_", str(e["program"]))
        for fact, prefix in _FACT_PREFIX.items():
            v = e.get(fact)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"{prefix}_{prog}"] = float(v)
    return out


def collect_compile_events(records: list[dict]) -> list[dict]:
    """All ``compile_events`` entries across a run's records, in order."""
    out: list[dict] = []
    for r in records:
        ev = r.get("compile_events") if isinstance(r, dict) else None
        if isinstance(ev, list):
            out.extend(e for e in ev if isinstance(e, dict))
    return out
