"""Run-JSONL summarizer: per-phase time share, throughput trend, stalls.

``python -m estorch_tpu.obs summarize run.jsonl`` answers the three
questions every perf PR and every wedged run raises:

1. *Where does the time go?* — per-phase share aggregated from the span
   breakdown each record carries (top-level phases only; nested
   ``parent/child`` spans are listed under their parent).
2. *Is it getting slower?* — first-half vs second-half env-steps/s.
3. *Did it stall?* — generations whose wall time is a large multiple of
   the median, plus (``--heartbeat``) the live last-phase/age of a run
   that never finished.

``--selfcheck`` validates the module's golden record against the record
schema — run in CI (run_lint.sh) so ``ES._base_record`` drift and schema
drift fail fast, before a consumer parses mismatched JSONL.
"""

from __future__ import annotations

import json
import math

from .recorder import STALE_AFTER_S, read_heartbeat

# record schema: key -> (types, required).  Floats accept ints (JSON
# round-trips 1.0 as 1); NaN/inf are legal values (failed generations).
RECORD_SCHEMA: dict[str, tuple[tuple[type, ...], bool]] = {
    "generation": ((int,), True),
    "reward_max": ((float, int), True),
    "reward_mean": ((float, int), True),
    "reward_min": ((float, int), False),
    "n_failed": ((int,), False),
    "best_reward": ((float, int), True),
    "improved_best": ((bool,), False),
    "env_steps": ((int,), True),
    "env_steps_per_sec": ((float, int), True),
    "grad_norm": ((float, int), False),
    "sigma": ((float, int), False),
    "wall_time_s": ((float, int), True),
    "phases": ((dict,), False),
    # performance attribution (obs/profile/): the compile ledger flushes
    # into whichever record follows a compile; the analytic cost model
    # rides the run's first record only
    "compile_events": ((list,), False),
    "cost_model": ((dict,), False),
    # async scheduler accounting (algo/scheduler.py, docs/async.md):
    # consumed/fresh/folded/stale_discarded per update + overlap facts
    "async": ((dict,), False),
    # scenario suite (estorch_tpu/scenarios, docs/scenarios.md):
    # per-variant fitness block — n_variants + per-variant counts/mean/best
    "scenarios": ((dict,), False),
}

# integer accounting keys an ``async`` block must carry (the zero-drop
# contract: consumed = fresh + folded, discards counted)
ASYNC_REQUIRED_KEYS = ("consumed", "fresh", "folded", "stale_discarded")

# a record shaped exactly like ES._base_record + span merge emits — the
# selfcheck fixture.  If _base_record changes shape, update BOTH (the
# tier-1 test_obs.py run-produced-records check catches a one-sided edit).
GOLDEN_RECORD = {
    "generation": 0,
    "reward_max": -120.5,
    "reward_mean": -400.25,
    "reward_min": -800.0,
    "n_failed": 0,
    "best_reward": -120.5,
    "improved_best": True,
    "env_steps": 819200,
    "env_steps_per_sec": 512000.0,
    "grad_norm": 0.731,
    "sigma": 0.05,
    "wall_time_s": 1.6,
    "phases": {"sample": 0.01, "eval": 1.2, "update": 0.3,
               "update/obsnorm_merge": 0.05},
    "compile_events": [
        {"program": "generation_step", "compile_s": 24.8, "generation": 0,
         "xla_flops": 7.1e12, "peak_bytes": 2.5e9, "first_call": True},
    ],
    "cost_model": {"schema": 1, "flops_per_env_step": 8704,
                   "bytes_per_env_step": 17924,
                   "per_generation": {"sample": {"flops": 7.3e7,
                                                 "bytes": 1.1e8},
                                      "update": {"flops": 3.7e7,
                                                 "bytes": 5.5e7}},
                   "population": 4096, "param_dim": 4481,
                   "noise_dim": 4481, "mirrored": True, "low_rank": 0,
                   "episodes_per_member": 1, "dtype_bytes": 4,
                   "matmul_shapes": [[3, 64], [64, 64], [64, 1]],
                   "env_steps_per_generation": 819200},
}


def validate_record(rec: dict) -> list[str]:
    """Schema problems in one record ([] when clean)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    for key, (types, required) in RECORD_SCHEMA.items():
        if key not in rec:
            if required:
                problems.append(f"missing required key {key!r}")
            continue
        v = rec[key]
        # bool is an int subclass — don't let True satisfy an int field
        if isinstance(v, bool) and bool not in types:
            problems.append(f"{key!r} is bool, expected "
                            f"{'/'.join(t.__name__ for t in types)}")
        elif not isinstance(v, types):
            problems.append(f"{key!r} is {type(v).__name__}, expected "
                            f"{'/'.join(t.__name__ for t in types)}")
    phases = rec.get("phases")
    if isinstance(phases, dict):
        for name, dur in phases.items():
            if not isinstance(name, str):
                problems.append(f"phase key {name!r} is not a string")
            elif (not isinstance(dur, (int, float))
                  or isinstance(dur, bool) or dur < 0):
                problems.append(f"phase {name!r} duration {dur!r} is not a "
                                "non-negative number")
    a = rec.get("async")
    if isinstance(a, dict):
        for key in ASYNC_REQUIRED_KEYS:
            v = a.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(f"async.{key} {v!r} is not a "
                                "non-negative int")
        if (isinstance(a.get("consumed"), int)
                and isinstance(a.get("fresh"), int)
                and isinstance(a.get("folded"), int)
                and a["consumed"] != a["fresh"] + a["folded"]):
            problems.append(
                f"async accounting broken: consumed {a['consumed']} != "
                f"fresh {a['fresh']} + folded {a['folded']}")
    sc = rec.get("scenarios")
    if isinstance(sc, dict):
        nv = sc.get("n_variants")
        if not isinstance(nv, int) or isinstance(nv, bool) or nv < 1:
            problems.append(f"scenarios.n_variants {nv!r} is not a "
                            "positive int")
        else:
            for key in ("counts", "mean", "best"):
                v = sc.get(key)
                if not isinstance(v, list) or len(v) != nv:
                    problems.append(
                        f"scenarios.{key} is not a length-{nv} list")
                elif key == "counts" and any(
                        not isinstance(c, int) or isinstance(c, bool)
                        or c < 0 for c in v):
                    problems.append("scenarios.counts has a negative "
                                    "or non-int entry")
                elif key != "counts" and any(
                        not (x is None or (isinstance(x, (int, float))
                                           and not isinstance(x, bool)))
                        for x in v):
                    problems.append(f"scenarios.{key} has a non-numeric "
                                    "entry")
    for i, e in enumerate(rec.get("compile_events") or []):
        if not isinstance(e, dict) or not isinstance(e.get("program"), str):
            problems.append(f"compile_events[{i}] lacks a program name")
        elif (not isinstance(e.get("compile_s"), (int, float))
              or isinstance(e.get("compile_s"), bool)
              or e["compile_s"] < 0):
            problems.append(f"compile_events[{i}] compile_s "
                            f"{e.get('compile_s')!r} is not a "
                            "non-negative number")
    return problems


def load_records(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def load_records_tolerant(path: str) -> tuple[list[dict], int]:
    """Like :func:`load_records`, but a malformed FINAL line is dropped
    instead of raised: an append-only run JSONL whose writer crashed (or
    was SIGKILLed — the supervised case) legitimately ends in a partial
    line, and the post-mortem tools (`obs summarize`, `obs trace`) exist
    for exactly those runs.  Returns ``(records, n_dropped)`` so the CLI
    can say the tail was dropped; garbage EARLIER in the file still
    raises — that is corruption, not a crash artifact."""
    with open(path) as f:
        lines = [(i, ln) for i, ln in enumerate(f.read().splitlines(), 1)
                 if ln.strip()]
    records: list[dict] = []
    for pos, (lineno, ln) in enumerate(lines):
        try:
            records.append(json.loads(ln))
        except ValueError as e:
            if pos == len(lines) - 1 and records:
                # a crash artifact is a torn tail BEHIND valid records;
                # a file whose only line is malformed is the wrong file,
                # not a truncated run
                return records, 1
            raise ValueError(f"line {lineno}: {e}") from e
    return records, 0


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return float("nan")
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


STALL_FACTOR = 5.0  # a generation this many × the median wall time stalls

# TAIL-HEAVY async queue-wait callout: p99/p50 beyond this ratio AND
# p99 above this floor.  The floor matters because the histogram ladder
# clamps sub-10µs waits to its underflow midpoint — a fast healthy fold
# loop can show a huge RATIO whose absolute p99 is half a millisecond,
# which is not a diagnosis worth shouting about
TAIL_RATIO_THRESHOLD = 10.0
TAIL_P99_FLOOR_S = 0.05

# WORST-VARIANT callout (scenario suite): a variant whose aggregated
# mean fitness lags the cross-variant family median by more than this
# many cross-variant MADs is called out — one systematically-losing
# scenario hiding inside a healthy-looking family mean is exactly what
# per-variant accounting exists to surface (docs/scenarios.md)
SCENARIO_MAD_FACTOR = 2.0


def _scenarios_section(records: list[dict]) -> tuple[dict | None,
                                                     str | None]:
    """(scenarios summary, diagnosis clause) aggregated over the run's
    per-generation blocks, or (None, None) for un-randomized runs.
    Count-weighted per-variant means, run-best bests, summed counts —
    the stdlib twin of scenarios/fitness.py's numpy aggregation (this
    module stays stdlib-only)."""
    blocks = [r["scenarios"] for r in records
              if isinstance(r.get("scenarios"), dict)
              and isinstance(r["scenarios"].get("n_variants"), int)]
    if not blocks:
        return None, None
    width = max(int(b["n_variants"]) for b in blocks)
    counts = [0] * width
    wsum = [0.0] * width
    wcnt = [0.0] * width
    best: list[float | None] = [None] * width

    def num(x):
        return (float(x) if isinstance(x, (int, float))
                and not isinstance(x, bool) and math.isfinite(x) else None)

    for b in blocks:
        cs = b.get("counts") or []
        ms = b.get("mean") or []
        bs = b.get("best") or []
        for v in range(min(width, len(cs))):
            c = int(cs[v]) if isinstance(cs[v], int) else 0
            counts[v] += c
            m = num(ms[v]) if v < len(ms) else None
            if m is not None and c > 0:
                wsum[v] += m * c
                wcnt[v] += c
            bb = num(bs[v]) if v < len(bs) else None
            if bb is not None:
                best[v] = bb if best[v] is None else max(best[v], bb)
    means = [wsum[v] / wcnt[v] if wcnt[v] else None for v in range(width)]
    section = {
        "n_variants": width,
        "coverage": round(sum(1 for c in counts if c) / width, 4),
        "counts": counts,
        "mean": [round(m, 4) if m is not None else None for m in means],
        "best": [round(b, 4) if b is not None else None for b in best],
    }
    clause = None
    finite = [m for m in means if m is not None]
    if len(finite) >= 3:
        med = _median(finite)
        mad = _median([abs(m - med) for m in finite])
        worst_v = min((v for v in range(width) if means[v] is not None),
                      key=lambda v: means[v])
        lag = med - means[worst_v]
        if mad > 0 and lag > SCENARIO_MAD_FACTOR * mad:
            section["worst_variant"] = {
                "variant": worst_v,
                "mean": round(means[worst_v], 4),
                "family_median": round(med, 4),
                "cross_variant_mad": round(mad, 4),
                "lag_in_mads": round(lag / mad, 2),
            }
            clause = (
                f"WORST-VARIANT: scenario variant {worst_v} mean "
                f"{means[worst_v]:.4g} lags the family median {med:.4g} "
                f"by {lag / mad:.1f}x the cross-variant MAD — one "
                "scenario is systematically losing; inspect its drawn "
                "constants (manifest config.scenarios)")
    return section, clause


# counters surfaced in the summary/diagnosis when nonzero — the
# resilience layer's evidence that a run survived faults rather than
# never seeing any (docs/resilience.md)
RESILIENCE_COUNTERS = (
    "generations_rejected",
    "generations_skipped",
    "workers_respawned",
    "members_retried",
    "rollout_failures",
    "supervisor_resumes",
    "chaos_worker_kills",
)

# serving counters (estorch_tpu/serve, docs/serving.md): present in a
# policy server's heartbeat — `requests_total` is the marker that the
# process being summarized serves traffic rather than training
SERVE_COUNTERS = (
    "requests_total",
    "batches_total",
    "batched_requests_total",
    "shed_total",
    "recompiles",
    "batch_errors_total",
    "reloads_total",
)


def _serving_block(counter_src: dict | None) -> tuple[dict | None, str | None]:
    """(serving summary, diagnosis clause) from a counter snapshot, or
    (None, None) when the counters aren't a policy server's."""
    if not counter_src or not counter_src.get("requests_total"):
        return None, None
    c = {k: counter_src.get(k, 0) for k in SERVE_COUNTERS}
    batches = c["batches_total"]
    mean_batch = (round(c["batched_requests_total"] / batches, 2)
                  if batches else None)
    serving = {
        "requests": int(c["requests_total"]),
        "batches": int(batches),
        "mean_batch": mean_batch,
        "shed": int(c["shed_total"]),
        "recompiles": int(c["recompiles"]),
    }
    if c["batch_errors_total"]:
        serving["batch_errors"] = int(c["batch_errors_total"])
    if c["reloads_total"]:
        serving["reloads"] = int(c["reloads_total"])
    clause = (f"serving: {serving['requests']} requests in "
              f"{serving['batches']} batches"
              + (f" (mean batch {mean_batch})" if mean_batch else ""))
    if serving["shed"]:
        clause += f", {serving['shed']} SHED — the server is saturated"
    if serving.get("batch_errors"):
        clause += f", {serving['batch_errors']} batch errors"
    return serving, clause


def _load_manifest_resilience(manifest_path: str | None) -> dict | None:
    """The run manifest's ``resilience`` section (supervisor-written
    restart provenance + cross-restart counter totals), or None."""
    if not manifest_path:
        return None
    try:
        with open(manifest_path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    res = data.get("resilience")
    return res if isinstance(res, dict) else None


def summarize(records: list[dict], heartbeat_path: str | None = None,
              manifest_path: str | None = None) -> dict:
    """Aggregate a run's records into the summary dict the CLI prints.

    With no records but a heartbeat (a policy server has no generation
    records), the summary is liveness + the serving counters — the
    ``summarize --heartbeat <path>`` form for serving processes."""
    if not records:
        out: dict = {"generations": 0}
        diagnosis = []
        hb = read_heartbeat(heartbeat_path) if heartbeat_path else None
        if hb is not None:
            out["heartbeat"] = hb
            state = (f"last phase={hb.get('phase')} beat "
                     f"{hb['age_s']:.0f}s ago")
            if hb.get("phase") == "drained":
                diagnosis.append(f"server drained cleanly; {state}")
            elif hb["age_s"] > STALE_AFTER_S:
                diagnosis.append(f"STALE heartbeat: {state} — the process "
                                 "is wedged or dead")
            else:
                diagnosis.append(f"heartbeat fresh: {state}")
            serving, clause = _serving_block(hb.get("counters"))
            if serving is not None:
                out["serving"] = serving
                diagnosis.append(clause)
        out["diagnosis"] = "; ".join(diagnosis) or "no records"
        return out
    # supervisor-replayed generations (the gap between the last checkpoint
    # and a crash) appear twice in an append-only run JSONL — keep the
    # LAST occurrence per generation (the replay that actually counted)
    # so totals/medians/trend describe the run, not the run plus replays.
    # Records without a generation key are kept as-is.
    seen_gens = [r.get("generation") for r in records]
    n_replayed = 0
    if len(set(g for g in seen_gens if g is not None)) < sum(
            1 for g in seen_gens if g is not None):
        last_idx = {g: i for i, g in enumerate(seen_gens) if g is not None}
        kept = [r for i, r in enumerate(records)
                if seen_gens[i] is None or last_idx[seen_gens[i]] == i]
        n_replayed = len(records) - len(kept)
        records = kept
    walls = [float(r.get("wall_time_s", 0.0)) for r in records]
    steps = [int(r.get("env_steps", 0)) for r in records]
    wall_total = sum(walls)

    # ---- per-phase aggregation (top-level vs nested) -------------------
    top: dict[str, float] = {}
    children: dict[str, dict[str, float]] = {}
    for r in records:
        for name, dur in (r.get("phases") or {}).items():
            if "/" in name:
                parent, _, child = name.partition("/")
                children.setdefault(parent, {})
                children[parent][child] = (
                    children[parent].get(child, 0.0) + float(dur))
            else:
                top[name] = top.get(name, 0.0) + float(dur)
    span_total = sum(top.values())
    phase_share = {
        name: {"seconds": round(sec, 4),
               "share": round(sec / span_total, 4) if span_total else 0.0}
        for name, sec in sorted(top.items(), key=lambda kv: -kv[1])
    }
    for parent, kids in children.items():
        if parent in phase_share:
            phase_share[parent]["children"] = {
                k: round(v, 4) for k, v in kids.items()}

    # ---- throughput trend ---------------------------------------------
    half = len(records) // 2
    trend = None
    if half >= 1 and sum(walls[:half]) > 0 and sum(walls[half:]) > 0:
        first = sum(steps[:half]) / sum(walls[:half])
        second = sum(steps[half:]) / sum(walls[half:])
        trend = {
            "first_half_steps_per_s": round(first, 1),
            "second_half_steps_per_s": round(second, 1),
            "ratio": round(second / first, 4) if first > 0 else None,
        }

    # ---- stall detection ----------------------------------------------
    med = _median(walls)
    stalls = [
        {"generation": int(r.get("generation", i)),
         "wall_time_s": round(w, 3),
         "x_median": round(w / med, 1)}
        for i, (r, w) in enumerate(zip(records, walls))
        if med > 0 and w > STALL_FACTOR * med
    ]

    # ---- async scheduler section (records carrying an "async" block) --
    async_recs = [r["async"] for r in records
                  if isinstance(r.get("async"), dict)]
    async_block = None
    if async_recs:
        consumed = sum(int(a.get("consumed", 0)) for a in async_recs)
        folded = sum(int(a.get("folded", 0)) for a in async_recs)
        discarded = sum(int(a.get("stale_discarded", 0))
                        for a in async_recs)
        oes = [a["overlap_efficiency"] for a in async_recs
               if isinstance(a.get("overlap_efficiency"), (int, float))
               and not isinstance(a.get("overlap_efficiency"), bool)]
        async_block = {
            "updates": len(async_recs),
            "consumed": consumed,
            "folded": folded,
            "stale_discarded": discarded,
            "stale_reuse_ratio": (round(folded / consumed, 4)
                                  if consumed else None),
            "overlap_efficiency": (round(_median(oes), 4) if oes
                                   else None),
            "max_staleness": max((int(a.get("max_staleness", 0))
                                  for a in async_recs), default=0),
        }
        # queue-wait / staleness quantiles: the LAST record's block is
        # the run-cumulative histogram state (algo/scheduler.py), so it
        # IS the run's distribution summary
        for key in ("queue_wait_s", "staleness_q"):
            qs = async_recs[-1].get(key)
            if (isinstance(qs, dict)
                    and isinstance(qs.get("p50"), (int, float))
                    and isinstance(qs.get("p99"), (int, float))):
                async_block[key] = {"p50": float(qs["p50"]),
                                    "p99": float(qs["p99"])}
        qw = async_block.get("queue_wait_s")
        if qw and qw["p50"] > 0:
            async_block["queue_wait_tail_ratio"] = round(
                qw["p99"] / qw["p50"], 2)

    scenarios_section, scenario_clause = _scenarios_section(records)

    diagnosis = []
    if stalls:
        worst = max(stalls, key=lambda s: s["x_median"])
        diagnosis.append(
            f"gen {worst['generation']} took {worst['x_median']}x the "
            f"median generation ({worst['wall_time_s']}s vs {med:.3f}s)")
    if trend and trend["ratio"] is not None and trend["ratio"] < 0.8:
        diagnosis.append(
            f"throughput decayed to {trend['ratio']:.0%} of the first half")
    manifest_res = _load_manifest_resilience(manifest_path)
    run_completed = bool(manifest_res and manifest_res.get("completed"))
    hb = None
    if heartbeat_path:
        hb = read_heartbeat(heartbeat_path)
        if hb is None:
            diagnosis.append(
                f"heartbeat unreadable at {heartbeat_path} — run never "
                "started telemetry, or the path is wrong")
        else:
            state = (f"last phase={hb.get('phase')} "
                     f"gen={hb.get('generation')} "
                     f"beat {hb['age_s']:.0f}s ago")
            if hb["age_s"] > STALE_AFTER_S and run_completed:
                # the supervisor recorded clean completion: an old beat is
                # the FINAL child's last state, not a wedge
                diagnosis.append(f"run completed (supervised); {state}")
            elif hb["age_s"] > STALE_AFTER_S:
                diagnosis.append(f"STALE heartbeat: {state} — the run is "
                                 "wedged or dead, not slow")
            else:
                diagnosis.append(f"heartbeat fresh: {state}")

    # ---- resilience: counters + supervisor restart provenance ----------
    # manifest counters are cross-restart totals (the supervisor sums each
    # child's last heartbeat) — prefer them over the live heartbeat's,
    # which only covers the CURRENT child
    counter_src = None
    if manifest_res and isinstance(manifest_res.get("counters"), dict):
        counter_src = manifest_res["counters"]
    elif hb and isinstance(hb.get("counters"), dict):
        counter_src = hb["counters"]
    counters = None
    if counter_src is not None:
        counters = {k: counter_src[k] for k in RESILIENCE_COUNTERS
                    if counter_src.get(k)}
        hits = [f"{int(counters[k])} {k}" for k in counters]
        if hits:
            diagnosis.append("resilience: " + ", ".join(hits))
    serving, serve_clause = _serving_block(counter_src)
    if serve_clause:
        diagnosis.append(serve_clause)
    restarts = None
    if manifest_res is not None:
        n_restarts = int(manifest_res.get("restart_count", 0))
        restarts = {
            "count": n_restarts,
            "completed": manifest_res.get("completed"),
            "reasons": [r.get("reason") for r in
                        manifest_res.get("restarts", [])],
        }
        if n_restarts:
            # reasons may be absent/truncated in a hand-edited or partial
            # manifest — diagnostics must degrade, never crash
            last = (f" (last: {restarts['reasons'][-1]})"
                    if restarts["reasons"] else "")
            diagnosis.append(
                f"supervisor restarted the run {n_restarts}x{last}")
    if n_replayed:
        diagnosis.append(
            f"{n_replayed} replayed generation record"
            f"{'s' if n_replayed != 1 else ''} deduped (re-run after a "
            "restart resumed from an earlier checkpoint)")
    if async_block:
        clause = (f"async: {async_block['folded']}/"
                  f"{async_block['consumed']} results folded stale "
                  f"(ratio {async_block['stale_reuse_ratio']})")
        if async_block["stale_discarded"]:
            clause += (f", {async_block['stale_discarded']} DISCARDED "
                       "past the staleness horizon")
        diagnosis.append(clause)
        ratio = async_block.get("queue_wait_tail_ratio")
        if ratio is not None and ratio > TAIL_RATIO_THRESHOLD:
            qw = async_block["queue_wait_s"]
            if qw["p99"] >= TAIL_P99_FLOOR_S:
                diagnosis.append(
                    f"TAIL-HEAVY async queue wait: p99 {qw['p99']}s is "
                    f"{ratio}x p50 {qw['p50']}s — a few results wait far "
                    "longer than typical (stragglers or a starved fold "
                    "loop); check async/eval_s and stale discards")
    if scenarios_section is not None:
        diagnosis.append(
            f"scenarios: {scenarios_section['n_variants']} variants, "
            f"{scenarios_section['coverage']:.0%} covered")
        if scenario_clause:
            diagnosis.append(scenario_clause)
    if not diagnosis:
        diagnosis.append("steady: no stalls, no throughput decay")

    out = {
        "generations": len(records),
        "wall_time_s": round(wall_total, 3),
        "env_steps": sum(steps),
        "env_steps_per_sec": (round(sum(steps) / wall_total, 1)
                              if wall_total > 0 else None),
        "span_coverage": (round(span_total / wall_total, 4)
                          if wall_total > 0 and span_total else 0.0),
        "phase_share": phase_share,
        "throughput": trend,
        "stalls": stalls,
        "diagnosis": "; ".join(diagnosis),
    }
    if hb is not None:
        out["heartbeat"] = hb
    if counters:
        out["counters"] = counters
    if serving is not None:
        out["serving"] = serving
    if restarts is not None:
        out["restarts"] = restarts
    if async_block is not None:
        out["async"] = async_block
    if scenarios_section is not None:
        out["scenarios"] = scenarios_section
    return out


def _format_serving(s: dict) -> list[str]:
    sv = s.get("serving")
    if not sv:
        return []
    line = (f"serving          {sv['requests']:,} requests  "
            f"{sv['batches']:,} batches")
    if sv.get("mean_batch"):
        line += f"  mean batch {sv['mean_batch']}"
    line += f"  shed={sv['shed']}  recompiles={sv['recompiles']}"
    return [line]


def format_summary(s: dict) -> str:
    """Human rendering of :func:`summarize`'s dict."""
    if not s.get("generations"):
        if s.get("serving") or s.get("heartbeat"):
            return "\n".join(_format_serving(s)
                             + [f"diagnosis        {s['diagnosis']}"])
        return "no records"
    lines = [
        f"generations      {s['generations']}",
        f"wall time        {s['wall_time_s']:.3f}s",
        f"env steps        {s['env_steps']:,}",
        f"env steps/s      {s['env_steps_per_sec']:,}"
        if s["env_steps_per_sec"] is not None else "env steps/s      n/a",
    ]
    if s["phase_share"]:
        lines.append(f"phase share      (covers "
                     f"{s['span_coverage']:.0%} of wall)")
        for name, row in s["phase_share"].items():
            bar = "#" * max(1, int(40 * row["share"]))
            lines.append(f"  {name:<14} {row['share']:7.1%}  "
                         f"{row['seconds']:9.3f}s  {bar}")
            for child, sec in row.get("children", {}).items():
                lines.append(f"    └ {child:<12} {'':7}  {sec:9.3f}s")
    else:
        lines.append("phase share      none recorded (telemetry disabled?)")
    t = s.get("throughput")
    if t:
        lines.append(
            f"throughput       {t['first_half_steps_per_s']:,} → "
            f"{t['second_half_steps_per_s']:,} steps/s "
            f"(x{t['ratio']})")
    if s.get("counters"):
        lines.append("resilience       " + "  ".join(
            f"{k}={int(v)}" for k, v in s["counters"].items()))
    a = s.get("async")
    if a:
        line = (f"async            {a['updates']} updates  "
                f"{a['folded']}/{a['consumed']} folded stale")
        if a.get("stale_reuse_ratio") is not None:
            line += f" (ratio {a['stale_reuse_ratio']})"
        if a.get("overlap_efficiency") is not None:
            line += f"  overlap {a['overlap_efficiency']}"
        line += f"  discarded={a['stale_discarded']}"
        lines.append(line)
        qw, st = a.get("queue_wait_s"), a.get("staleness_q")
        if qw or st:
            tail = "async tails      "
            if qw:
                tail += (f"queue-wait p50={qw['p50']}s "
                         f"p99={qw['p99']}s")
                if a.get("queue_wait_tail_ratio") is not None:
                    tail += f" (p99/p50 {a['queue_wait_tail_ratio']}x)"
            if st:
                tail += (f"  staleness p50={st['p50']} "
                         f"p99={st['p99']}")
            lines.append(tail)
    sc = s.get("scenarios")
    if sc:
        means = [m for m in sc["mean"] if m is not None]
        line = (f"scenarios        {sc['n_variants']} variants  "
                f"coverage {sc['coverage']:.0%}")
        if means:
            line += (f"  mean {min(means):.4g}..{max(means):.4g}")
        lines.append(line)
        wv = sc.get("worst_variant")
        if wv:
            lines.append(
                f"  └ worst v{wv['variant']:<3} mean {wv['mean']:.4g}  "
                f"({wv['lag_in_mads']}x MAD below median "
                f"{wv['family_median']:.4g})")
    lines.extend(_format_serving(s))
    if s.get("restarts") and s["restarts"]["count"]:
        lines.append(f"restarts         {s['restarts']['count']} "
                     f"(completed={s['restarts']['completed']})")
    lines.append(f"diagnosis        {s['diagnosis']}")
    return "\n".join(lines)


def selfcheck() -> list[str]:
    """Schema self-validation for CI ([] = healthy).

    Checks the golden record against the schema, that a synthetic run
    through :func:`summarize` produces the promised keys, and that the
    stall detector fires on an obvious stall.
    """
    problems = list(validate_record(GOLDEN_RECORD))
    # a deliberately-broken record must FAIL validation (the validator
    # itself could silently rot into accepting everything)
    broken = dict(GOLDEN_RECORD, env_steps="many")
    broken.pop("reward_mean")
    if not validate_record(broken):
        problems.append("validator accepted a broken record")
    recs = []
    for g in range(6):
        r = dict(GOLDEN_RECORD, generation=g,
                 wall_time_s=1.0 if g != 4 else 30.0)
        recs.append(json.loads(json.dumps(r)))  # via-JSON: CLI-equivalent
    s = summarize(recs)
    for key in ("generations", "wall_time_s", "env_steps",
                "env_steps_per_sec", "phase_share", "throughput",
                "stalls", "diagnosis"):
        if key not in s:
            problems.append(f"summary missing {key!r}")
    if not s.get("stalls"):
        problems.append("stall detector missed a 30x-median generation")
    share = s.get("phase_share", {})
    for phase in ("sample", "eval", "update"):
        if phase not in share:
            problems.append(f"phase_share missing {phase!r}")
    if "update" in share and "obsnorm_merge" not in share["update"].get(
            "children", {}):
        problems.append("nested span update/obsnorm_merge not aggregated")
    total_share = sum(row["share"] for row in share.values())
    if share and not math.isclose(total_share, 1.0, abs_tol=1e-3):
        problems.append(f"top-level shares sum to {total_share}, not 1")
    if format_summary(s) == "no records":
        problems.append("format_summary rendered nothing")

    # async scheduler surfacing (algo/scheduler.py): records carrying an
    # "async" block must validate, aggregate into the async section, and
    # render — and broken accounting must FAIL validation
    async_rec = dict(GOLDEN_RECORD, generation=6,
                     **{"async": {"consumed": 16, "fresh": 10, "folded": 6,
                                  "stale_discarded": 1, "max_staleness": 2,
                                  "mean_lambda": 0.91,
                                  "overlap_efficiency": 0.8,
                                  "dispatches": [6, 7],
                                  "consumed_dispatches": [[5, 10], [6, 6]],
                                  "discarded_dispatches": [[4, 1]],
                                  "queue_wait_s": {"p50": 0.004,
                                                   "p99": 0.09},
                                  "staleness_q": {"p50": 0.0, "p99": 2.0}}})
    problems += [f"async golden: {p}"
                 for p in validate_record(json.loads(json.dumps(async_rec)))]
    broken_async = dict(GOLDEN_RECORD,
                        **{"async": {"consumed": 16, "fresh": 10,
                                     "folded": 3, "stale_discarded": 0}})
    if not validate_record(broken_async):
        problems.append("validator accepted consumed != fresh + folded")
    sa = summarize(recs + [json.loads(json.dumps(async_rec))])
    ab = sa.get("async")
    if not ab or ab.get("folded") != 6 or ab.get("consumed") != 16:
        problems.append("summary missed the async accounting block")
    if ab and ab.get("stale_reuse_ratio") != round(6 / 16, 4):
        problems.append("stale_reuse_ratio mis-derived")
    if "async" not in sa.get("diagnosis", ""):
        problems.append("diagnosis missed the async section")
    if "DISCARDED" not in sa["diagnosis"]:
        problems.append("diagnosis missed the stale-discard callout")
    if "async" not in format_summary(sa):
        problems.append("format_summary dropped the async block")
    # tail health: queue-wait/staleness quantiles surface, and a
    # p99/p50 ratio > 10 is called out as TAIL-HEAVY in the diagnosis
    if ab and ab.get("queue_wait_s", {}).get("p99") != 0.09:
        problems.append("async queue-wait quantiles not surfaced")
    if ab and ab.get("staleness_q", {}).get("p99") != 2.0:
        problems.append("async staleness quantiles not surfaced")
    if ab and ab.get("queue_wait_tail_ratio") != round(0.09 / 0.004, 2):
        problems.append("queue-wait p99/p50 ratio mis-derived")
    if "TAIL-HEAVY" not in sa.get("diagnosis", ""):
        problems.append("diagnosis missed the tail-heavy queue-wait "
                        "callout (p99/p50 > 10)")
    if "queue-wait" not in format_summary(sa):
        problems.append("format_summary dropped the async tails line")
    # a healthy tail (ratio <= 10) must NOT be called out
    calm = dict(async_rec)
    calm["async"] = dict(async_rec["async"],
                         **{"queue_wait_s": {"p50": 0.004, "p99": 0.02}})
    sc = summarize(recs + [json.loads(json.dumps(calm))])
    if "TAIL-HEAVY" in sc.get("diagnosis", ""):
        problems.append("tail-heavy callout fired on a 5x (healthy) "
                        "p99/p50 ratio")
    # ...nor must a huge RATIO whose absolute p99 is sub-millisecond
    # (the histogram ladder clamps tiny p50s — ratio alone is not a
    # diagnosis)
    fast = dict(async_rec)
    fast["async"] = dict(async_rec["async"],
                         **{"queue_wait_s": {"p50": 9.1e-06,
                                             "p99": 0.0005}})
    sf = summarize(recs + [json.loads(json.dumps(fast))])
    if "TAIL-HEAVY" in sf.get("diagnosis", ""):
        problems.append("tail-heavy callout fired on a sub-millisecond "
                        "p99 (ladder-floor ratio artifact)")
    # a synchronous run must not grow an async section
    if summarize(recs).get("async"):
        problems.append("sync run grew an async section")

    # scenario suite (estorch_tpu/scenarios, docs/scenarios.md): records
    # carrying a per-variant fitness block must validate, aggregate into
    # the scenarios section count-weighted, and surface a worst-variant
    # callout when one variant lags the family by >2x the cross-variant
    # MAD — while a balanced family stays quiet
    def scen_rec(gen, means):
        return dict(GOLDEN_RECORD, generation=gen, scenarios={
            "n_variants": len(means), "counts": [4] * len(means),
            "mean": means, "best": [m + 5.0 for m in means]})

    lag = [-100.0, -102.0, -98.0, -101.0, -99.0, -400.0]
    sr = [json.loads(json.dumps(scen_rec(g, lag))) for g in range(3)]
    problems += [f"scenario golden: {p}" for p in validate_record(sr[0])]
    broken_sc = dict(GOLDEN_RECORD, scenarios={
        "n_variants": 4, "counts": [1, 2], "mean": [0.0], "best": "big"})
    if not validate_record(broken_sc):
        problems.append("validator accepted a malformed scenarios block")
    ssc = summarize(recs + sr)
    blk = ssc.get("scenarios")
    if not blk or blk.get("n_variants") != 6:
        problems.append("summary missed the scenarios section")
    if blk and blk.get("coverage") != 1.0:
        problems.append("scenario coverage mis-derived")
    if blk and blk.get("mean", [None])[0] != -100.0:
        problems.append("per-variant mean not count-weighted across "
                        "generations")
    if blk and blk.get("best", [None])[0] != -95.0:
        problems.append("per-variant best not aggregated as run max")
    if not blk or blk.get("worst_variant", {}).get("variant") != 5:
        problems.append("worst-variant callout missed a 2x-MAD laggard")
    if "WORST-VARIANT" not in ssc.get("diagnosis", ""):
        problems.append("diagnosis missed the worst-variant callout")
    if "scenarios" not in format_summary(ssc):
        problems.append("format_summary dropped the scenarios block")
    balanced = [json.loads(json.dumps(
        scen_rec(g, [-100.0, -102.0, -98.0, -101.0, -99.0, -103.0])))
        for g in range(3)]
    sb = summarize(recs + balanced)
    if "WORST-VARIANT" in sb.get("diagnosis", ""):
        problems.append("worst-variant callout fired on a balanced family")
    if summarize(recs).get("scenarios"):
        problems.append("un-randomized run grew a scenarios section")

    # resilience surfacing: a chaos run's rejected-generation counters and
    # the supervisor's restart provenance must show up in the summary —
    # validated against synthetic heartbeat/manifest files so drift fails
    # here, not in a post-mortem
    import os
    import tempfile
    import time as _time

    with tempfile.TemporaryDirectory() as d:
        hb_path = os.path.join(d, "heartbeat.json")
        with open(hb_path, "w") as f:
            json.dump({"ts": _time.time(), "pid": 1, "phase": "eval",
                       "generation": 3,
                       "counters": {"generations_rejected": 2,
                                    "workers_respawned": 1}}, f)
        mf_path = os.path.join(d, "manifest.json")
        with open(mf_path, "w") as f:
            json.dump({"resilience": {
                "restart_count": 1, "completed": True,
                "restarts": [{"reason": "child died with exit code -9"}],
                "counters": {"generations_rejected": 2,
                             "generations_skipped": 1}}}, f)
        sr = summarize(recs, heartbeat_path=hb_path, manifest_path=mf_path)
        if sr.get("counters", {}).get("generations_rejected") != 2:
            problems.append("summary missed generations_rejected counter")
        if sr.get("restarts", {}).get("count") != 1:
            problems.append("summary missed supervisor restart count")
        if "restarted" not in sr["diagnosis"]:
            problems.append("diagnosis missed the supervisor restart")
        if "resilience" not in format_summary(sr):
            problems.append("format_summary dropped resilience counters")
        # heartbeat-only fallback (no supervisor/manifest in the run)
        sh = summarize(recs, heartbeat_path=hb_path)
        if sh.get("counters", {}).get("workers_respawned") != 1:
            problems.append("heartbeat counters not surfaced sans manifest")

        # serving process: no generation records, counters in the
        # heartbeat (estorch_tpu/serve writes exactly this shape) — the
        # summarize --heartbeat form must surface the serving section
        serve_hb = os.path.join(d, "serve_heartbeat.json")
        with open(serve_hb, "w") as f:
            json.dump({"ts": _time.time(), "pid": 2, "phase": "serving",
                       "generation": 0,
                       "counters": {"requests_total": 640,
                                    "batches_total": 40,
                                    "batched_requests_total": 640,
                                    "shed_total": 3,
                                    "recompiles": 5}}, f)
        ss = summarize([], heartbeat_path=serve_hb)
        sv = ss.get("serving")
        if not sv or sv.get("requests") != 640 or sv.get("mean_batch") != 16:
            problems.append("serving counters not aggregated from a "
                            "server heartbeat")
        if "serving" not in ss.get("diagnosis", ""):
            problems.append("diagnosis missed the serving section")
        if "SHED" not in ss["diagnosis"]:
            problems.append("diagnosis missed serving shed (saturation)")
        if "serving" not in format_summary(ss):
            problems.append("format_summary dropped the serving block")
        # a TRAINING run's summary must not grow a serving section just
        # because resilience counters exist
        if summarize(recs, heartbeat_path=hb_path).get("serving"):
            problems.append("non-serving run grew a serving section")
    return problems
