"""Record sinks: where per-generation telemetry records go.

Migrated from ``utils/metrics.py`` (which remains as a re-export shim).
Every generation ``ES.train`` emits a structured record (``_base_record``
— reward stats, env-steps/sec, grad norm, per-phase span times, novelty
stats for the NS family); these sinks plug into ``train(log_fn=...)``:

- JsonlSink: one JSON object per line, append-only, crash-safe.
- TensorBoardSink: optional (gated on torch.utils.tensorboard); nested
  ``phases`` dicts flatten to ``es/phase/<name>`` scalars.
- MultiSink: fan-out to several sinks + optional console echo.

The historical ``*Writer`` names are aliases of the same classes.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Sequence


class JsonlSink:
    """Append each generation record as one JSON line."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fh = open(self.path, "a", buffering=1)

    def __call__(self, record: dict) -> None:
        self._fh.write(json.dumps(record, default=float) + "\n")

    def close(self) -> None:
        self._fh.close()

    @staticmethod
    def read(path: str) -> list[dict]:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]


class TensorBoardSink:
    """Scalars to TensorBoard via torch.utils.tensorboard (optional dep)."""

    def __init__(self, logdir: str):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError as e:  # tensorboard not installed in this image
            raise ImportError(
                "TensorBoardSink needs the tensorboard package; use "
                "JsonlSink in this environment"
            ) from e
        self._w = SummaryWriter(logdir)

    def __call__(self, record: dict) -> None:
        step = record.get("generation", 0)
        for k, v in record.items():
            if isinstance(v, (int, float)) and k != "generation":
                self._w.add_scalar(f"es/{k}", v, step)
            elif k == "phases" and isinstance(v, dict):
                for phase, dur in v.items():
                    if isinstance(dur, (int, float)):
                        self._w.add_scalar(f"es/phase/{phase}", dur, step)

    def close(self) -> None:
        self._w.close()


class MultiSink:
    """Fan a record out to several sinks; optionally echo to stdout."""

    def __init__(self, sinks: Sequence[Callable[[dict], None]],
                 echo: bool = False):
        self.writers = list(sinks)
        self.echo = echo

    def __call__(self, record: dict) -> None:
        for w in self.writers:
            w(record)
        if self.echo:
            print(
                f"gen {record.get('generation', '?'):>4}  "
                f"max {record.get('reward_max', float('nan')):9.2f}  "
                f"mean {record.get('reward_mean', float('nan')):9.2f}  "
                f"steps/s {record.get('env_steps_per_sec', 0):,.0f}"
            )

    def close(self) -> None:
        for w in self.writers:
            if hasattr(w, "close"):
                w.close()


# historical names (pre-obs utils.metrics surface) — same classes
JsonlWriter = JsonlSink
TensorBoardWriter = TensorBoardSink
MultiWriter = MultiSink
