"""Counters/gauges registry — the numeric facts of one run.

Counters are monotone (``inc``), gauges are last-write-wins (``gauge``);
both live in one flat name → value dict so exporting a run's telemetry
is one ``snapshot()``.  Thread-safe: the host backend's worker threads
increment rollout-failure counters concurrently with the training loop.

Names follow a short dotted convention (no enforced schema — the
registry is generic): ``env_steps``, ``generations``, ``recompiles``,
``rollout_failures``, ``stage_timeouts``, ``peak_rss_mb``,
``compile_time_s``.
"""

from __future__ import annotations

import threading


class Counters:
    """Flat registry of counters (monotone) and gauges (overwrite)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values: dict[str, float] = {}

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = value

    def get(self, name: str, default: float = 0) -> float:
        with self._lock:
            return self._values.get(name, default)

    def snapshot(self) -> dict[str, float]:
        """Point-in-time copy (safe to serialize while workers run)."""
        with self._lock:
            return dict(self._values)

    def sample_peak_rss(self) -> float:
        """Record the process's peak RSS as the ``peak_rss_mb`` gauge.

        ``getrusage`` is a single syscall (~1µs) — cheap enough to call
        once per generation.  ru_maxrss is KiB on Linux, bytes on macOS.
        """
        import resource
        import sys

        div = 2**20 if sys.platform == "darwin" else 2**10
        mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / div
        self.gauge("peak_rss_mb", round(mb, 3))
        return mb


class NullCounters(Counters):
    """Inert registry for disabled telemetry.  Engines increment
    counters unconditionally (engine code must not branch on the hub's
    state), so a DISABLED hub — in particular the process-wide shared
    NULL_TELEMETRY default every engine starts with — must swallow
    writes: otherwise unrelated engines in one process would aggregate
    `recompiles` etc. into one global grab-bag."""

    def inc(self, name: str, n: float = 1) -> None:
        pass

    def gauge(self, name: str, value: float) -> None:
        pass

    def sample_peak_rss(self) -> float:
        return 0.0
