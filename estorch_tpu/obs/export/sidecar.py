"""Metrics sidecar: scrape a training run FROM OUTSIDE its process.

``python -m estorch_tpu.obs serve-metrics --run-dir D`` (or, on a host
whose jax import chain is wedged, ``python
estorch_tpu/obs/export/sidecar.py --run-dir D``) serves Prometheus text
exposition at ``/metrics`` built entirely from files in the run
directory:

* ``heartbeat.json`` — the live child's last beat (phase, generation,
  counter snapshot), written atomically by the obs hub;
* ``counters.json`` — the supervisor's atomically-published
  cross-restart counter TOTALS (resilience/supervisor.py writes it each
  time a child exits, folding that child's final heartbeat in).

The composition rule makes scraped totals monotone across restarts
without double counting: ``total = published + live`` where the live
heartbeat's counters only count when the beat is NEWER than the
published snapshot's ``through_ts`` (an exited child's final beat is
already folded into the published totals — adding it again would double
count exactly the child the supervisor just buried).

This is why the sidecar exists at all: a wedged or supervised-restarting
trainer cannot answer HTTP itself, but its heartbeat file keeps telling
the story — the sidecar is a separate stdlib-only process whose answers
survive every child death.  It never imports jax (nor the estorch_tpu
package when run as a file), so it starts in milliseconds and cannot be
taken down by the very runtime wedge it reports on.

``/healthz`` answers liveness OF THE WATCHED RUN as JSON (heartbeat age
+ staleness verdict); the sidecar itself answering at all is its own
liveness.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

if __package__:
    from ..hist import export_snapshots, merge_snapshots
    from ..recorder import STALE_AFTER_S, read_heartbeat
    from .prometheus import render_exposition
else:  # file-run (wedged-jax host): load siblings without any package init
    import importlib.util

    def _load(name: str, *rel: str):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            *rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _recorder = _load("_estorch_obs_recorder", os.pardir, "recorder.py")
    _prom = _load("_estorch_obs_prometheus", "prometheus.py")
    _hist = _load("_estorch_obs_hist", os.pardir, "hist.py")
    STALE_AFTER_S = _recorder.STALE_AFTER_S
    read_heartbeat = _recorder.read_heartbeat
    render_exposition = _prom.render_exposition
    merge_snapshots = _hist.merge_snapshots
    export_snapshots = _hist.export_snapshots

COUNTERS_FILENAME = "counters.json"
COUNTERS_SCHEMA = 1


def publish_counters(run_dir: str, counters: dict, through_ts: float,
                     extra: dict | None = None,
                     hists: dict | None = None) -> str:
    """Atomically publish cross-restart counter totals into ``run_dir``.

    ``through_ts``: the heartbeat timestamp these totals already include
    — the sidecar only adds a live heartbeat's counters on top when the
    beat is newer than this.  Same tmp+rename contract as the heartbeat,
    so a scrape can never read a half-written snapshot.  ``hists``:
    cross-restart histogram totals (``Histogram.to_dict`` snapshots per
    name, bucket-wise summed by the supervisor) riding the same file so
    a dead child's latency DISTRIBUTION survives it, not just its sums.
    """
    path = os.path.join(os.path.abspath(run_dir), COUNTERS_FILENAME)
    payload = {
        "schema": COUNTERS_SCHEMA,
        "through_ts": float(through_ts),
        "counters": {k: v for k, v in (counters or {}).items()
                     if isinstance(v, (int, float))
                     and not isinstance(v, bool)},
    }
    if hists:
        payload["hists"] = {k: v for k, v in hists.items()
                            if isinstance(v, dict)}
    if extra:
        payload.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, default=float)
    os.replace(tmp, path)
    return path


def read_published_counters(run_dir: str) -> dict | None:
    """The published snapshot, or None when absent/corrupt/unknown-schema
    (an unsupervised run never publishes one — the heartbeat alone then
    carries the counters)."""
    path = os.path.join(run_dir, COUNTERS_FILENAME)
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if (data.get("schema") != COUNTERS_SCHEMA
            or not isinstance(data.get("counters"), dict)):
        return None
    return data


def compose_totals(published: dict | None, heartbeat: dict | None) -> dict:
    """published totals + live child's counters (see module docstring)."""
    totals: dict = {}
    through_ts = 0.0
    if published is not None:
        through_ts = float(published.get("through_ts", 0.0))
        for k, v in published["counters"].items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                totals[k] = totals.get(k, 0) + v
    if heartbeat is not None and float(heartbeat.get("ts", 0.0)) > through_ts:
        for k, v in (heartbeat.get("counters") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                totals[k] = totals.get(k, 0) + v
    return totals


def compose_hists(published: dict | None, heartbeat: dict | None) -> dict:
    """Published histogram totals + the live child's snapshots, under
    the same newer-than-``through_ts`` rule as :func:`compose_totals` —
    bucket ladders add exactly, so scraped tail quantiles stay truthful
    across restarts without double counting a buried child's beat."""
    total: dict = {}
    through_ts = 0.0
    if published is not None:
        through_ts = float(published.get("through_ts", 0.0))
        if isinstance(published.get("hists"), dict):
            total = published["hists"]
    live = None
    if (heartbeat is not None
            and float(heartbeat.get("ts", 0.0)) > through_ts
            and isinstance(heartbeat.get("hists"), dict)):
        live = heartbeat["hists"]
    return merge_snapshots(total, live)


class MetricsSidecar:
    """Loopback HTTP server exposing one run directory as /metrics."""

    def __init__(self, run_dir: str, *, host: str = "127.0.0.1",
                 port: int = 0, stale_after_s: float = STALE_AFTER_S):
        self.run_dir = os.path.abspath(run_dir)
        self.stale_after_s = float(stale_after_s)
        self._httpd = _SidecarHttpd((host, int(port)), _make_handler(self))
        self.host, self.port = self._httpd.server_address[:2]

    # ----------------------------------------------------------- scrape

    @property
    def heartbeat_path(self) -> str:
        return os.path.join(self.run_dir, "heartbeat.json")

    def scrape(self) -> str:
        """One /metrics body — re-reads the run-dir files every call, so
        the sidecar holds no state a child restart could invalidate."""
        hb = read_heartbeat(self.heartbeat_path)
        published = read_published_counters(self.run_dir)
        totals = compose_totals(published, hb)
        hists = compose_hists(published, hb)
        extra = {}
        if published is not None and "restart_count" in published:
            extra["supervisor_restarts"] = published["restart_count"]
        if published is not None and "completed" in published:
            # lets an alert tell "done" from "dead": after the run ends
            # the heartbeat goes stale and estorch_up drops either way,
            # but a completed run publishes its verdict first
            extra["run_completed"] = 1.0 if published["completed"] else 0.0
        return render_exposition(totals, hb,
                                 stale_after_s=self.stale_after_s,
                                 extra_gauges=extra,
                                 histograms=export_snapshots(hists) or None)

    def health(self) -> tuple[int, dict]:
        hb = read_heartbeat(self.heartbeat_path)
        if hb is None:
            return 503, {"ok": False, "run_dir": self.run_dir,
                         "error": "no readable heartbeat — run never "
                                  "started telemetry, or wrong dir"}
        stale = hb["age_s"] > self.stale_after_s
        return (503 if stale else 200), {
            "ok": not stale,
            "run_dir": self.run_dir,
            "age_s": round(hb["age_s"], 3),
            "stale": stale,
            "phase": hb.get("phase"),
            "generation": hb.get("generation"),
        }

    # -------------------------------------------------------- lifecycle

    def serve_forever(self) -> None:
        self._serving = True
        self._httpd.serve_forever(poll_interval=0.1)

    def start_background(self) -> threading.Thread:
        self._serving = True
        t = threading.Thread(target=self.serve_forever,
                             name="obs-metrics-sidecar", daemon=True)
        t.start()
        return t

    def close(self) -> None:
        # shutdown() blocks on the serve loop's acknowledgement — if the
        # loop never ran (scrape()-only use), it would wait forever
        if getattr(self, "_serving", False):
            self._httpd.shutdown()
        self._httpd.server_close()


class _SidecarHttpd(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


def _make_handler(sidecar: MetricsSidecar):
    class SidecarHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # scrapes every few seconds: quiet
            pass

        def _reply(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                self._reply(200, sidecar.scrape().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/healthz":
                code, payload = sidecar.health()
                self._reply(code, json.dumps(payload).encode(),
                            "application/json")
            else:
                self._reply(404, json.dumps(
                    {"error": f"no route {self.path!r}"}).encode(),
                    "application/json")

    return SidecarHandler


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs serve-metrics",
        description="Prometheus /metrics sidecar over a run directory "
                    "(docs/observability.md, Export)")
    p.add_argument("--run-dir", required=True, metavar="DIR",
                   help="run directory holding heartbeat.json (and, for "
                        "supervised runs, counters.json)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9321,
                   help="0 picks an ephemeral port (see --port-file)")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="atomically write {host,port,pid} JSON once bound")
    p.add_argument("--stale-after-s", type=float, default=STALE_AFTER_S,
                   help="heartbeat age beyond which estorch_up reads 0")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"serve-metrics: no such run dir {args.run_dir!r}",
              file=sys.stderr)
        return 2
    sidecar = MetricsSidecar(args.run_dir, host=args.host, port=args.port,
                             stale_after_s=args.stale_after_s)
    # ready-to-paste targets.json entry for the fleet collector
    # (obs/agg/) — same stanza (and same wildcard-bind substitution) as
    # the serve server's /stats: 0.0.0.0 is not routable FROM the
    # collector's host, so pasting it would scrape the wrong machine
    stanza_host = sidecar.host
    if stanza_host in ("0.0.0.0", "::", ""):
        import socket as _socket

        stanza_host = _socket.getfqdn() or _socket.gethostname()
    print(json.dumps({"ready": True,
                      "url": f"http://{sidecar.host}:{sidecar.port}",
                      "run_dir": sidecar.run_dir, "pid": os.getpid(),
                      "collector_target": {
                          "name": os.path.basename(sidecar.run_dir)
                                  or "run",
                          "url": f"http://{stanza_host}:{sidecar.port}"
                                 "/metrics",
                      }}),
          flush=True)
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": sidecar.host, "port": sidecar.port,
                       "pid": os.getpid()}, f)
        os.replace(tmp, args.port_file)
    import signal

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    sidecar.start_background()
    stop.wait()
    sidecar.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
