"""``obs regress`` — a statistical perf gate over committed baselines.

The BENCH_r01–r05 trajectory has been checked by humans reading
markdown; this module makes it gate itself: compare a current
measurement (a run JSONL, a ``bench.py`` output line, or a bench A/B
JSONL) against a committed baseline (the ``BENCH_*.json`` schema) and
emit a machine-readable verdict that ``bench.py --regress`` and
``run_lint.sh`` consume.

The statistics follow the ``--obs-ab`` discipline (bench.py): single
runs on a loaded shared-core host swing far more than any effect worth
gating on, so verdicts compare **robust medians**, and the pass/fail
threshold is a **noise band learned from the repeats themselves** — the
scaled median-absolute-deviation of whichever side carries repeats
(per-generation rates in a run JSONL, per-repeat rows in a bench
artifact), floored at ``min_band_pct`` so a suspiciously quiet sample
cannot manufacture false alarms.  A drop beyond the band is a
regression; a gain beyond it is reported as an improvement (still exit
0 — the gate is one-sided by design).

Deliberately stdlib-only and importable WITHOUT the package: bench.py
(whose driver must never import jax — the round-1 wedge lesson) loads
this file directly, the same way it loads ``obs/recorder.py``.

Accepted measurement files (auto-detected per line):

* ``BENCH_r*.json``     — ``{"parsed": {"metric", "value", ...}}``
* bench stdout line     — ``{"metric", "value", ...}``
* bench A/B JSONL rows  — ``{"label", "rate", ...}`` (``--label``
  filters; rows with null rate are skipped)
* run JSONL records     — ``{"generation", "env_steps_per_sec", ...}``
  (supervisor-replayed generations are deduped, keeping the last)

Two safeguards beyond the aggregate gate:

* **platform guard** — a measurement that records its platform (the
  ``device_probe`` extras new BENCH artifacts carry, or the platform
  noted in the legacy unit string) is refused against a baseline from a
  DIFFERENT platform: a cpu-fallback run "regressing" against a TPU
  baseline is a platform mismatch, not a perf verdict, and emitting a
  bogus verdict would be worse than an error;
* **phase localization** (``obs regress --phases``, ``compare_phases``)
  — per-phase medians of the span seconds every record carries
  (``record["phases"]``, PR 2), each gated by its own learned noise
  band, so the verdict names the phase that moved (``eval`` got 30%
  slower) instead of drowning a localized regression in aggregate
  host-load noise.
"""

from __future__ import annotations

import json
import math

DEFAULT_MIN_BAND_PCT = 5.0
REGRESS_SCHEMA = 1


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return float("nan")
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _noise_band_pct(xs: list[float]) -> float:
    """Relative noise of one sample set as a percentage of its median:
    1.4826·MAD/median (the robust sigma estimate) — 0 when there are
    fewer than 3 repeats to learn from."""
    if len(xs) < 3:
        return 0.0
    med = _median(xs)
    if not med or not math.isfinite(med):
        return 0.0
    mad = _median([abs(x - med) for x in xs])
    return 100.0 * 1.4826 * mad / abs(med)


def extract_samples(lines: list[dict], label: str | None = None
                    ) -> tuple[list[float], str]:
    """(samples, metric name) from parsed measurement lines (see module
    docstring for the accepted shapes).  Raises ValueError when nothing
    usable is found — a gate that silently passes on an empty file is
    worse than no gate."""
    samples: list[float] = []
    metric = "env_steps_per_sec"
    gen_last: dict[int, float] = {}  # replay dedup: last occurrence wins
    order: list[int] = []
    for row in lines:
        if not isinstance(row, dict):
            continue
        if label is not None and row.get("label") not in (None, label):
            continue
        parsed = row.get("parsed")
        if isinstance(parsed, dict) and isinstance(
                parsed.get("value"), (int, float)):
            samples.append(float(parsed["value"]))
            metric = str(parsed.get("metric", metric))
        elif isinstance(row.get("value"), (int, float)) and "metric" in row:
            samples.append(float(row["value"]))
            metric = str(row["metric"])
        elif isinstance(row.get("rate"), (int, float)):
            samples.append(float(row["rate"]))
            metric = "rate"
        elif isinstance(row.get("env_steps_per_sec"), (int, float)):
            g = row.get("generation")
            if isinstance(g, int):
                if g not in gen_last:
                    order.append(g)
                gen_last[g] = float(row["env_steps_per_sec"])
            else:
                samples.append(float(row["env_steps_per_sec"]))
    samples.extend(gen_last[g] for g in order)
    samples = [s for s in samples if math.isfinite(s)]
    if not samples:
        raise ValueError(
            "no usable samples (expected BENCH_*.json 'parsed.value', a "
            "bench {'metric','value'} line, {'rate'} rows, or run-JSONL "
            "'env_steps_per_sec' records)")
    return samples, metric


def load_rows(path: str) -> list[dict]:
    """The raw parsed rows of one measurement file: whole-file JSON
    first (BENCH_*.json is an indented object), then JSONL with a
    tolerated truncated FINAL line (crash artifact); garbage earlier in
    the file is an error, as is an empty file."""
    with open(path) as f:
        text = f.read()
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty file")
    try:
        return [json.loads(text)]
    except ValueError:
        rows: list[dict] = []
        for i, ln in enumerate(lines):
            try:
                rows.append(json.loads(ln))
            except ValueError as e:
                if i == len(lines) - 1:
                    break  # truncated tail: a crash mid-append
                raise ValueError(f"{path} line {i + 1}: {e}") from e
        return rows


def load_measurement(path: str, label: str | None = None
                     ) -> tuple[list[float], str]:
    """Read one measurement file (JSON object or JSONL) into samples —
    :func:`load_rows`'s tolerance rules, then :func:`extract_samples`."""
    rows = load_rows(path)  # its errors already carry the path
    try:
        return extract_samples(rows, label=label)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from e


def compare(current: list[float], baseline: list[float],
            metric: str = "rate",
            min_band_pct: float = DEFAULT_MIN_BAND_PCT) -> dict:
    """Median-vs-median verdict with a learned noise band.

    ``verdict``: ``"pass"`` | ``"regress"``; ``drop_pct`` is positive
    when the current measurement is slower than the baseline.
    """
    cur_med = _median(current)
    base_med = _median(baseline)
    band = max(float(min_band_pct),
               _noise_band_pct(current), _noise_band_pct(baseline))
    drop = ((base_med - cur_med) / base_med * 100.0) if base_med else 0.0
    verdict = "regress" if drop > band else "pass"
    return {
        "schema": REGRESS_SCHEMA,
        "verdict": verdict,
        "metric": metric,
        "current_median": round(cur_med, 3),
        "baseline_median": round(base_med, 3),
        "drop_pct": round(drop, 2),
        "band_pct": round(band, 2),
        "n_current": len(current),
        "n_baseline": len(baseline),
        "improved": drop < -band,
    }


def measurement_platform(rows: list[dict]) -> str | None:
    """The platform a measurement was taken on, when it says: the typed
    ``extras.device_probe.platform`` new BENCH artifacts carry, a bare
    ``platform`` key (stage rows), or — legacy artifacts — the platform
    noted in the unit string (``"..., cpu)"`` / the old cpu-fallback
    prose).  None when nothing states it (run JSONLs don't)."""
    for row in rows:
        if not isinstance(row, dict):
            continue
        for holder in (row, row.get("extras") or {}):
            if not isinstance(holder, dict):
                continue
            probe = holder.get("device_probe")
            if isinstance(probe, dict) and probe.get("platform"):
                return str(probe["platform"])
            if isinstance(holder.get("platform"), str):
                return holder["platform"]
        parsed = row.get("parsed")
        unit = (parsed or {}).get("unit") if isinstance(parsed, dict) \
            else row.get("unit")
        if isinstance(unit, str):
            low = unit.lower()
            if "cpu fallback" in low or "cpu)" in low or ", cpu" in low:
                return "cpu"
            if "tpu)" in low or ", tpu" in low:
                return "tpu"
    return None


def ensure_same_platform(cur_platform: str | None,
                         base_platform: str | None,
                         cur_what: str = "current",
                         base_what: str = "baseline") -> None:
    """Raise when both sides state a platform and they differ — a
    platform mismatch is an ERROR, not a verdict: a cpu-fallback
    artifact "regressing" 90% against a TPU baseline says nothing about
    performance, and a bogus verdict would gate on it.  The ONE guard
    shared by ``compare_files`` and ``bench.py --regress``."""
    if cur_platform and base_platform and cur_platform != base_platform:
        raise ValueError(
            f"platform mismatch: {cur_what} was measured on "
            f"{cur_platform!r} but {base_what} on {base_platform!r} — "
            "perf verdicts only mean something within one platform "
            "(re-baseline, or pass a same-platform artifact)")


def compare_files(current_path: str, baseline_path: str,
                  label: str | None = None,
                  min_band_pct: float = DEFAULT_MIN_BAND_PCT) -> dict:
    cur_rows = load_rows(current_path)
    base_rows = load_rows(baseline_path)
    cur_platform = measurement_platform(cur_rows)
    base_platform = measurement_platform(base_rows)
    ensure_same_platform(cur_platform, base_platform,
                         cur_what=f"current {current_path}",
                         base_what=f"baseline {baseline_path}")
    try:
        cur, metric = extract_samples(cur_rows, label=label)
    except ValueError as e:
        raise ValueError(f"{current_path}: {e}") from e
    try:
        base, base_metric = extract_samples(base_rows, label=label)
    except ValueError as e:
        raise ValueError(f"{baseline_path}: {e}") from e
    out = compare(cur, base, metric=metric, min_band_pct=min_band_pct)
    if base_metric != metric:
        out["warning"] = (f"metric mismatch: current={metric!r} "
                          f"baseline={base_metric!r}")
    if cur_platform or base_platform:
        out["platform"] = cur_platform or base_platform
    return out


# ---------------------------------------------------------------------
# phase-localized gate: per-phase medians with per-phase noise bands
# ---------------------------------------------------------------------

def expand_embedded_rows(rows: list[dict]) -> list[dict]:
    """BENCH_r06+ artifacts carry their per-generation phase records and
    per-request latency rows EMBEDDED (``phase_rows`` / ``tail_rows``
    lists), so one committed JSON file is both the aggregate baseline
    and the phase/tail baseline.  This flattens them for the phase and
    tail extractors; the aggregate extractor deliberately does NOT
    expand (embedded per-generation rates are per-host, the headline
    ``parsed.value`` is per-chip — mixing units would corrupt the
    median)."""
    out: list[dict] = []
    for row in rows:
        if not isinstance(row, dict):
            continue
        out.append(row)
        for key in ("phase_rows", "tail_rows"):
            sub = row.get(key)
            if isinstance(sub, list):
                out.extend(r for r in sub if isinstance(r, dict))
    return out


def extract_phase_samples(records: list[dict]) -> dict[str, list[float]]:
    """Per-generation seconds for every TOP-LEVEL phase across a run's
    records (``record["phases"]``; nested ``parent/child`` spans are the
    parent's internal breakdown and are not separately gated).
    Supervisor-replayed generations are deduped keeping the last, the
    same rule the aggregate extractor applies."""
    gen_last: dict[tuple, dict] = {}
    order: list[tuple] = []
    anon: list[dict] = []
    for row in expand_embedded_rows(records):
        if not isinstance(row.get("phases"), dict):
            continue
        g = row.get("generation")
        if isinstance(g, int):
            # replay dedup is per measurement run: embedded baseline rows
            # carry a 'repeat' stamp (bench --capture-baseline), and
            # collapsing generation g across repeats would silently drop
            # all but the last repeat's samples
            key = (row.get("repeat"), g)
            if key not in gen_last:
                order.append(key)
            gen_last[key] = row["phases"]
        else:
            anon.append(row["phases"])
    out: dict[str, list[float]] = {}
    for phases in [gen_last[g] for g in order] + anon:
        for name, dur in phases.items():
            if (isinstance(dur, (int, float)) and not isinstance(dur, bool)
                    and math.isfinite(dur) and "/" not in name):
                out.setdefault(name, []).append(float(dur))
    return out


def compare_phases(current: list[dict], baseline: list[dict],
                   min_band_pct: float = DEFAULT_MIN_BAND_PCT) -> dict:
    """Phase-localized verdict over two runs' records: each shared
    top-level phase's median SECONDS gated by that phase's own learned
    noise band — the verdict names the phase(s) that slowed instead of
    drowning them in the aggregate.  Phases are durations, so here a
    regression is the current median coming out ABOVE the band (slower),
    the mirror of the rate gate's below."""
    cur_phases = extract_phase_samples(current)
    base_phases = extract_phase_samples(baseline)
    # mixed-schema degrade: a side with NO phase rows at all (a pre-r06
    # BENCH artifact, or a telemetry-off run) gets a one-line diagnosis
    # naming the side — not a traceback, and never a bogus verdict
    if not base_phases or not cur_phases:
        side = "baseline" if not base_phases else "current"
        raise ValueError(
            f"{side} measurement carries no per-phase rows — a pre-r06 "
            "BENCH artifact (no embedded 'phase_rows') or a "
            "telemetry-disabled run; pick a baseline captured with "
            "`bench.py --capture-baseline` (BENCH_r06+) or a run JSONL "
            "with 'phases' records")
    shared = sorted(set(cur_phases) & set(base_phases))
    if not shared:
        raise ValueError(
            "no shared top-level phases between the two runs (phase "
            "names disjoint — different engines or renamed spans?)")
    phases: dict[str, dict] = {}
    regressed: list[str] = []
    for name in shared:
        cur, base = cur_phases[name], base_phases[name]
        cur_med, base_med = _median(cur), _median(base)
        band = max(float(min_band_pct),
                   _noise_band_pct(cur), _noise_band_pct(base))
        slowdown = ((cur_med - base_med) / base_med * 100.0) if base_med \
            else 0.0
        verdict = "regress" if slowdown > band else "pass"
        if verdict == "regress":
            regressed.append(name)
        phases[name] = {
            "verdict": verdict,
            "current_median_s": round(cur_med, 6),
            "baseline_median_s": round(base_med, 6),
            "slowdown_pct": round(slowdown, 2),
            "band_pct": round(band, 2),
            "improved": slowdown < -band,
            "n_current": len(cur),
            "n_baseline": len(base),
        }
    return {
        "schema": REGRESS_SCHEMA,
        "verdict": "regress" if regressed else "pass",
        "metric": "phase_seconds",
        "phases": phases,
        "regressed_phases": regressed,
    }


def compare_phase_files(current_path: str, baseline_path: str,
                        min_band_pct: float = DEFAULT_MIN_BAND_PCT) -> dict:
    try:
        return compare_phases(load_rows(current_path),
                              load_rows(baseline_path),
                              min_band_pct=min_band_pct)
    except ValueError as e:
        raise ValueError(f"{current_path} vs {baseline_path}: {e}") from e


# ---------------------------------------------------------------------
# tail gate: p99-vs-p99 with its own learned MAD band
# ---------------------------------------------------------------------
#
# Medians can't see the 1% of requests a shed or a recompile ruins: a
# 5× slowdown on 1% of samples moves p50 by ~nothing and p99 by ~5×.
# ``obs regress --tail`` gates a chosen upper quantile per GROUP (phase
# of a run JSONL, endpoint of a latency JSONL) against the baseline's
# same quantile, with a noise band learned from the quantile estimator
# itself: each side is split into k deterministic interleaved
# subsamples, the quantile computed per subsample, and the band is the
# scaled MAD of those estimates — a tail quantile is far noisier than a
# median, and gating it against the MEDIAN's band would cry wolf.
# Verdicts NAME the quantile and the group ("p99 of 'eval'").

TAIL_QUANTILE = 0.99
TAIL_FOLDS = 5


def _quantile(xs: list[float], q: float) -> float:
    """Nearest-rank quantile (the loadgen/hist convention)."""
    s = sorted(xs)
    if not s:
        return float("nan")
    k = max(1, math.ceil(q * len(s)))
    return s[k - 1]


def _tail_band_pct(xs: list[float], q: float,
                   folds: int = TAIL_FOLDS) -> float:
    """Relative noise of the ``q``-quantile ESTIMATOR on this sample:
    scaled MAD of the quantile across ``folds`` deterministic
    interleaved subsamples, as a percentage of their median.  0 when
    there are too few samples to subsample (the floor then rules)."""
    if len(xs) < folds * 4:
        return 0.0
    qs = [_quantile(xs[i::folds], q) for i in range(folds)]
    med = _median(qs)
    if not med or not math.isfinite(med):
        return 0.0
    mad = _median([abs(x - med) for x in qs])
    return 100.0 * 1.4826 * mad / abs(med)


def extract_tail_groups(rows: list[dict]) -> dict[str, list[float]]:
    """Per-group duration samples for the tail gate.

    Two row shapes, combinable: latency rows (``{"latency_s": x,
    "endpoint": "/predict"}`` — the loadgen ``--latencies-out`` format)
    group by endpoint; run-JSONL generation records contribute their
    top-level phase seconds (replay-deduped, like the phase gate) plus a
    ``wall_time_s`` group."""
    groups: dict[str, list[float]] = {}
    # extract_phase_samples expands embedded rows ITSELF — it must see
    # the original rows, or the still-embedded copies inside the outer
    # row would be walked twice and double-count generation-less records
    for name, samples in extract_phase_samples(rows).items():
        groups.setdefault(name, []).extend(samples)
    expanded = expand_embedded_rows(rows)
    for row in expanded:
        v = row.get("latency_s")
        if (isinstance(v, (int, float)) and not isinstance(v, bool)
                and math.isfinite(v)):
            name = str(row.get("endpoint") or "latency")
            groups.setdefault(name, []).append(float(v))
    # wall_time_s follows the same replay-dedup rule as the phase
    # samples above: a supervisor-replayed generation appears twice in
    # the JSONL and must not be double-weighted in the quantile (but a
    # different 'repeat' is a different measurement run, not a replay)
    gen_last: dict[tuple, float] = {}
    order: list[tuple] = []
    anon: list[float] = []
    for r in expanded:
        w = r.get("wall_time_s")
        if (not isinstance(w, (int, float)) or isinstance(w, bool)
                or not math.isfinite(w)):
            continue
        g = r.get("generation")
        if isinstance(g, int):
            key = (r.get("repeat"), g)
            if key not in gen_last:
                order.append(key)
            gen_last[key] = float(w)
        else:
            anon.append(float(w))
    walls = [gen_last[g] for g in order] + anon
    if walls:
        groups.setdefault("wall_time_s", []).extend(walls)
    return groups


def compare_tail(current: list[dict], baseline: list[dict],
                 quantile: float = TAIL_QUANTILE,
                 min_band_pct: float = DEFAULT_MIN_BAND_PCT) -> dict:
    """Tail verdict over two measurements' rows: each shared group's
    ``quantile`` gated by that group's own learned quantile-estimator
    MAD band (durations: ABOVE the band = regress).  Each group also
    reports its p50 verdict under the median machinery, so "median
    passed, p99 regressed" is one artifact."""
    if not 0.5 <= quantile < 1.0:
        raise ValueError(f"tail quantile must be in [0.5, 1), got "
                         f"{quantile}")
    cur_groups = extract_tail_groups(current)
    base_groups = extract_tail_groups(baseline)
    # mixed-schema degrade (same contract as compare_phases): an empty
    # side is diagnosed on one line naming the side and the fix
    if not base_groups or not cur_groups:
        side = "baseline" if not base_groups else "current"
        raise ValueError(
            f"{side} measurement carries no tail rows — a pre-r06 BENCH "
            "artifact (no embedded 'phase_rows'/'tail_rows') or a "
            "measurement without {'latency_s','endpoint'} / "
            "'phases'/'wall_time_s' records; re-capture with `bench.py "
            "--capture-baseline` or `loadgen --latencies-out`")
    shared = sorted(set(cur_groups) & set(base_groups))
    if not shared:
        raise ValueError(
            "no shared tail groups between the two measurements (group "
            "names disjoint — different endpoints or renamed phases?)")
    qname = f"p{quantile * 100:g}"
    groups: dict[str, dict] = {}
    regressed: list[str] = []
    for name in shared:
        cur, base = cur_groups[name], base_groups[name]
        cur_q, base_q = _quantile(cur, quantile), _quantile(base, quantile)
        band = max(float(min_band_pct),
                   _tail_band_pct(cur, quantile),
                   _tail_band_pct(base, quantile))
        slowdown = ((cur_q - base_q) / base_q * 100.0) if base_q else 0.0
        verdict = "regress" if slowdown > band else "pass"
        if verdict == "regress":
            regressed.append(name)
        cur_med, base_med = _median(cur), _median(base)
        med_band = max(float(min_band_pct),
                       _noise_band_pct(cur), _noise_band_pct(base))
        med_slow = ((cur_med - base_med) / base_med * 100.0) if base_med \
            else 0.0
        groups[name] = {
            "verdict": verdict,
            "quantile": qname,
            "current_q_s": round(cur_q, 6),
            "baseline_q_s": round(base_q, 6),
            "slowdown_pct": round(slowdown, 2),
            "band_pct": round(band, 2),
            "improved": slowdown < -band,
            "median_verdict": ("regress" if med_slow > med_band
                               else "pass"),
            "current_median_s": round(cur_med, 6),
            "baseline_median_s": round(base_med, 6),
            "median_slowdown_pct": round(med_slow, 2),
            "n_current": len(cur),
            "n_baseline": len(base),
        }
    return {
        "schema": REGRESS_SCHEMA,
        "verdict": "regress" if regressed else "pass",
        "metric": "tail_seconds",
        "quantile": qname,
        "groups": groups,
        "regressed_groups": regressed,
    }


def compare_tail_files(current_path: str, baseline_path: str,
                       quantile: float = TAIL_QUANTILE,
                       min_band_pct: float = DEFAULT_MIN_BAND_PCT) -> dict:
    cur_rows = load_rows(current_path)
    base_rows = load_rows(baseline_path)
    # same platform guard as the aggregate gate: a cpu-fallback artifact
    # "tail-regressing" against a TPU baseline is a platform mismatch,
    # not a verdict
    ensure_same_platform(measurement_platform(cur_rows),
                         measurement_platform(base_rows),
                         cur_what=f"current {current_path}",
                         base_what=f"baseline {baseline_path}")
    try:
        return compare_tail(cur_rows, base_rows,
                            quantile=quantile, min_band_pct=min_band_pct)
    except ValueError as e:
        raise ValueError(f"{current_path} vs {baseline_path}: {e}") from e


def tail_selfcheck() -> list[str]:
    """The run_lint.sh gate for the tail gate ([] = healthy): a
    median-clean / p99-regressed pair — 2% of requests slowed 5×, the
    chaos-shed signature — must PASS every group's median verdict but be
    FLAGGED by the tail verdict, naming the quantile and the group; an
    identical-distribution rerun must pass; the latency-row file round
    trip must agree with the in-memory path."""
    import os
    import random
    import tempfile

    problems: list[str] = []

    def lat_rows(seed: int, n: int = 2000, slow_every: int = 0
                 ) -> list[dict]:
        rng = random.Random(seed)
        rows = []
        for i in range(n):
            v = 0.010 * (1.0 + rng.uniform(-0.02, 0.02))
            if slow_every and i % slow_every == 0:
                v *= 5.0  # the 5x chaos slowdown on ~2% of requests
            rows.append({"endpoint": "/predict", "latency_s": v})
        return rows

    base = lat_rows(0)
    clean = compare_tail(lat_rows(1), base)
    if clean["verdict"] != "pass":
        problems.append(f"same-distribution rerun flagged: {clean}")
    tainted = compare_tail(lat_rows(2, slow_every=50), base)
    g = tainted["groups"].get("/predict", {})
    if tainted["verdict"] != "regress" or "/predict" not in \
            tainted["regressed_groups"]:
        problems.append(f"5x-on-2% tail regression not flagged: {tainted}")
    if tainted.get("quantile") != "p99" or g.get("quantile") != "p99":
        problems.append(f"verdict does not NAME the quantile: {tainted}")
    if g.get("median_verdict") != "pass":
        problems.append(
            f"median verdict should stay clean on a tail-only regression "
            f"(the whole point): {g}")

    # run-JSONL form: 1-in-50 generations' eval phase slowed 5x — the
    # median phase gate passes, the tail gate names 'eval'
    def gen_rows(seed: int, slow_every: int = 0) -> list[dict]:
        rng = random.Random(seed)
        rows = []
        for gdx in range(100):
            ev = 0.100 * (1.0 + rng.uniform(-0.02, 0.02))
            if slow_every and gdx % slow_every == 0:
                ev *= 5.0
            up = 0.020 * (1.0 + rng.uniform(-0.02, 0.02))
            rows.append({"generation": gdx, "wall_time_s": ev + up,
                         "env_steps_per_sec": 1000.0,
                         "phases": {"eval": ev, "update": up}})
        return rows

    base_g = gen_rows(3)
    cur_g = gen_rows(4, slow_every=50)
    med = compare_phases(cur_g, base_g)
    if med["verdict"] != "pass":
        problems.append(f"median phase gate flagged a tail-only "
                        f"regression: {med}")
    tail = compare_tail(cur_g, base_g)
    if "eval" not in tail["regressed_groups"]:
        problems.append(f"tail gate missed the eval-phase p99: {tail}")
    if "update" in tail["regressed_groups"]:
        problems.append(f"tail gate flagged the untouched update phase: "
                        f"{tail}")

    # supervisor-replayed generations must be deduped in EVERY group,
    # wall_time_s included (double-weighted duplicates skew the p99)
    replayed = base_g + [dict(base_g[0])]
    gg = extract_tail_groups(replayed)
    if len(gg["wall_time_s"]) != 100 or len(gg["eval"]) != 100:
        problems.append(
            f"replayed generation double-weighted in tail groups: "
            f"wall={len(gg['wall_time_s'])} eval={len(gg['eval'])}")

    # file round trip (the CLI path)
    with tempfile.TemporaryDirectory() as d:
        cur_path = os.path.join(d, "cur.jsonl")
        base_path = os.path.join(d, "base.jsonl")
        for path, rows in ((cur_path, lat_rows(2, slow_every=50)),
                           (base_path, base)):
            with open(path, "w") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
        v = compare_tail_files(cur_path, base_path)
        if (v["verdict"] != "regress"
                or v["regressed_groups"] != ["/predict"]):
            problems.append(f"file round trip disagreed: {v}")
        # cross-platform artifacts are an ERROR, never a tail verdict
        # (the same guard the aggregate gate applies)
        cpu_path = os.path.join(d, "cpu.jsonl")
        with open(cpu_path, "w") as f:
            f.write(json.dumps({"platform": "cpu"}) + "\n")
            for row in lat_rows(8):
                f.write(json.dumps(row) + "\n")
        tpu_path = os.path.join(d, "tpu.jsonl")
        with open(tpu_path, "w") as f:
            f.write(json.dumps({"platform": "tpu"}) + "\n")
            for row in base:
                f.write(json.dumps(row) + "\n")
        try:
            v = compare_tail_files(cpu_path, tpu_path)
            problems.append(f"cpu-vs-tpu tail comparison produced a "
                            f"verdict instead of a platform-mismatch "
                            f"error: {v}")
        except ValueError as e:
            if "platform mismatch" not in str(e):
                problems.append(f"cpu-vs-tpu tail error lacks the "
                                f"platform-mismatch diagnosis: {e}")
    return problems


# ---------------------------------------------------------------------
# selfcheck: the run_lint.sh gate for the gate
# ---------------------------------------------------------------------

def selfcheck() -> list[str]:
    """Prove the gate can tell signal from noise ([] = healthy):

    * an identical-run comparison (same samples both sides) passes;
    * a same-distribution rerun (fresh ±2% noise) passes;
    * a 30% slowdown injected into a copied baseline is flagged;
    * the file round trip (BENCH-style baseline vs run-JSONL current)
      produces the same verdicts the in-memory path does.
    """
    import os
    import random
    import tempfile

    problems: list[str] = []

    def synth(seed: int, scale: float = 1.0, n: int = 12) -> list[float]:
        rng = random.Random(seed)
        return [1000.0 * scale * (1.0 + rng.uniform(-0.02, 0.02))
                for _ in range(n)]

    base = synth(0)
    same = compare(list(base), list(base))
    if same["verdict"] != "pass" or abs(same["drop_pct"]) > 1e-9:
        problems.append(f"identical-run comparison did not pass: {same}")
    rerun = compare(synth(1), base)
    if rerun["verdict"] != "pass":
        problems.append(f"same-distribution rerun flagged as regression: "
                        f"{rerun}")
    slow = compare(synth(2, scale=0.70), base)
    if slow["verdict"] != "regress" or slow["drop_pct"] < 20.0:
        problems.append(f"30% injected slowdown not flagged: {slow}")
    fast = compare(synth(3, scale=1.30), base)
    if fast["verdict"] != "pass" or not fast["improved"]:
        problems.append(f"30% speedup misreported: {fast}")

    with tempfile.TemporaryDirectory() as d:
        # committed-baseline schema (a copied BENCH_*.json with the
        # synthetic slowdown injected into the current side)
        base_path = os.path.join(d, "BENCH_base.json")
        with open(base_path, "w") as f:
            json.dump({"n": 1, "rc": 0, "parsed": {
                "metric": "env_steps_per_sec_per_chip",
                "value": 1000.0, "unit": "env-steps/s/chip"}}, f)

        def write_run(path: str, rates: list[float]) -> None:
            with open(path, "w") as f:
                for g, r in enumerate(rates):
                    f.write(json.dumps({
                        "generation": g, "env_steps_per_sec": r,
                        "env_steps": 1000, "wall_time_s": 1000 / r,
                        "reward_mean": 0.0, "reward_max": 0.0,
                        "best_reward": 0.0}) + "\n")

        clean_path = os.path.join(d, "clean.jsonl")
        write_run(clean_path, synth(4))
        v = compare_files(clean_path, base_path)
        if v["verdict"] != "pass":
            problems.append(f"clean run vs committed baseline failed: {v}")
        slow_path = os.path.join(d, "slow.jsonl")
        write_run(slow_path, synth(5, scale=0.70))
        v = compare_files(slow_path, base_path)
        if v["verdict"] != "regress":
            problems.append(f"slowed run vs committed baseline passed: {v}")
        # a replayed generation (supervisor restart) must be deduped, not
        # averaged in twice
        with open(clean_path, "a") as f:
            f.write(json.dumps({"generation": 0,
                                "env_steps_per_sec": 1.0}) + "\n")
        cur, _ = load_measurement(clean_path)
        if len(cur) != 12:
            problems.append(f"replay dedup kept {len(cur)} samples, not 12")
        if min(cur) != 1.0:
            problems.append("replay dedup did not keep the LAST occurrence")
        # truncated tail (crash artifact) tolerated; empty file is an error
        with open(clean_path, "a") as f:
            f.write('{"generation": 99, "env_ste')
        try:
            load_measurement(clean_path)
        except ValueError as e:
            problems.append(f"truncated tail not tolerated: {e}")
        empty = os.path.join(d, "empty.jsonl")
        open(empty, "w").close()
        empty_raised = False
        try:
            load_measurement(empty)
        except ValueError:
            empty_raised = True
        if not empty_raised:
            problems.append("empty measurement file did not raise")
        # platform guard: a cpu-fallback artifact against a TPU baseline
        # must be a platform-mismatch ERROR, never a verdict
        tpu_base = os.path.join(d, "BENCH_tpu.json")
        with open(tpu_base, "w") as f:
            json.dump({"parsed": {"metric": "env_steps_per_sec_per_chip",
                                  "value": 5e6,
                                  "unit": "env-steps/s/chip (pendulum, "
                                          "tpu)"}}, f)
        cpu_cur = os.path.join(d, "BENCH_cpu.json")
        with open(cpu_cur, "w") as f:
            json.dump({"parsed": {"metric": "env_steps_per_sec_per_chip",
                                  "value": 4e4, "unit": "env-steps/s/chip"},
                       "extras": {"device_probe": {"status": "failed",
                                                   "platform": "cpu"}}}, f)
        try:
            v = compare_files(cpu_cur, tpu_base)
            problems.append(f"cpu-vs-tpu comparison produced a verdict "
                            f"instead of a platform-mismatch error: {v}")
        except ValueError as e:
            if "platform mismatch" not in str(e):
                problems.append(f"cpu-vs-tpu error lacks the platform-"
                                f"mismatch diagnosis: {e}")
    return problems
