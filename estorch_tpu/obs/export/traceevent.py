"""Perfetto/Chrome trace-event export of a run JSONL.

``python -m estorch_tpu.obs trace run.jsonl -o trace.json`` turns the
per-generation span breakdown every record already carries
(``record["phases"]``, nested ``parent/child`` names) into trace-event
JSON that ``ui.perfetto.dev`` / ``chrome://tracing`` render as a
timeline — the "where did generation 412 go" question answered by
looking, not by reading numbers.

Records carry durations, not wall timestamps (the JSONL stays one line
per generation), so the exporter SYNTHESIZES the timeline: generations
are laid end to end (``wall_time_s`` each), and inside a generation the
top-level phases are laid sequentially in record order with their
children nested at the parent's start.  The layout is a faithful
rendering of per-phase time *shares*; it does not claim sub-generation
ordering beyond what the record preserves.

A run that crossed Supervisor restarts renders as ONE timeline: the
records are split into per-child segments at replay boundaries
(generation numbers going backwards — the resume-from-checkpoint
signature) and, when a ``manifest.json`` with restart provenance is
beside the JSONL, at the generation each dead child had reached.  Each
segment becomes its own trace *process* lane keyed by the manifest's
provenance (the dead child's heartbeat pid, the restart reason), and the
boundary itself is an instant marker carrying the reason.

Compile-ledger entries (``record["compile_events"]``, obs/profile/
ledger.py) render as instant markers on a per-segment ``compiles`` lane
at the carrying generation's start — compile seconds and XLA cost facts
in the args, so "why is this generation wide" and "what did that
program cost to build" are answered on the same timeline.

Async runs get a causal ``async`` lane (docs/observability.md "Tails &
traces"): each record's ``async`` block names the dispatches it
snapshotted and the ``[dispatch, members]`` pairs it folded or
discarded, rendered as Perfetto FLOW ARROWS — a flow starts at the
dispatch instant, steps through each update that consumed part of it,
and finishes at the last fold/discard, so a stale dispatch links
visually to the exact update that folded it.

Optional extra lanes: ``--events ring.jsonl`` (a flight-recorder
``dump_jsonl``) and the run dir's heartbeat render as instant events on
a separate wall-clock lane (rebased to 0; the synthesized lanes and the
wall-clock lane deliberately do not share a clock and say so in their
names).

:func:`validate_trace` is the schema gate the tests and the e2e demo
use — "renders in Perfetto" approximated by "every event is a
well-formed trace event".
"""

from __future__ import annotations

import json

TRACE_PHASES = {"X", "B", "E", "i", "I", "C", "M", "s", "t", "f"}
_FLOW_PHASES = {"s", "t", "f"}  # flow events: start / step / finish
_WALL_PID = 0  # the wall-clock lane (flight recorder + heartbeat markers)


def _us(seconds: float) -> float:
    return round(float(seconds) * 1e6, 3)


def _segment_bounds(records: list[dict], manifest: dict | None
                    ) -> tuple[list[int], list[dict]]:
    """Record indices where a new child's records begin, plus the restart
    provenance rows (possibly empty) aligned to them best-effort."""
    gens = [r.get("generation") for r in records]
    bounds = [
        i for i in range(1, len(records))
        if gens[i] is not None and gens[i - 1] is not None
        and gens[i] <= gens[i - 1]
    ]
    restarts: list[dict] = []
    res = (manifest or {}).get("resilience")
    if isinstance(res, dict) and isinstance(res.get("restarts"), list):
        restarts = [r for r in res["restarts"] if isinstance(r, dict)]
    # checkpoint-aligned restarts leave no replay: derive the boundary
    # from the generation the dying child had reached (its last beat)
    for r in restarts[len(bounds):]:
        hb = r.get("heartbeat") or {}
        g = hb.get("generation")
        if g is None:
            continue
        for i in range(1, len(records)):
            if gens[i] is not None and gens[i] >= g and i not in bounds:
                bounds.append(i)
                break
    return sorted(set(bounds)), restarts


def _async_pairs(block: dict, key: str) -> list[tuple[int, int]]:
    """Well-formed ``(dispatch, count)`` pairs of one async-block list
    (malformed entries skipped — post-mortem inputs degrade, not crash)."""
    out = []
    for pair in block.get(key) or []:
        if (isinstance(pair, (list, tuple)) and len(pair) == 2
                and isinstance(pair[0], int) and isinstance(pair[1], int)):
            out.append((pair[0], pair[1]))
    return out


def export_trace(records: list[dict],
                 manifest: dict | None = None,
                 events: list[dict] | None = None,
                 heartbeat: dict | None = None) -> dict:
    """Build the trace-event dict (see module docstring)."""
    bounds, restarts = _segment_bounds(records, manifest)
    trace_events: list[dict] = []
    # async causality pre-scan: the LAST record touching a dispatch
    # (fold or discard) carries the flow FINISH; earlier touches are
    # flow steps — one arrow chain per dispatch id
    has_async = any(isinstance(r.get("async"), dict) for r in records)
    last_touch: dict[int, int] = {}
    for i, rec in enumerate(records):
        a = rec.get("async")
        if isinstance(a, dict):
            for d, _n in (_async_pairs(a, "consumed_dispatches")
                          + _async_pairs(a, "discarded_dispatches")):
                last_touch[d] = i
    flow_started: set[int] = set()

    def seg_pid(seg: int) -> int:
        if seg < len(restarts):
            pid = (restarts[seg].get("heartbeat") or {}).get("pid")
            if isinstance(pid, int):
                return pid
        if seg == len(bounds) and heartbeat is not None:
            pid = heartbeat.get("pid")
            if isinstance(pid, int):
                return pid
        return 100_000 + seg  # provenance unknown: synthetic stable id

    def add_process_meta(seg: int, pid: int) -> None:
        if seg < len(restarts):
            ended = restarts[seg].get("reason") or "restarted"
            name = f"child {seg} (pid {pid}) — {ended}"
        elif bounds:
            name = f"child {seg} (pid {pid}) — final"
        else:
            name = f"run (pid {pid})"
        trace_events.append({"ph": "M", "name": "process_name",
                             "pid": pid, "tid": 0,
                             "args": {"name": name}})
        lanes = [(1, "generations"), (2, "phases"), (3, "compiles")]
        if has_async:
            lanes.append((4, "async (dispatch→fold flows)"))
        for tid, tname in lanes:
            trace_events.append({"ph": "M", "name": "thread_name",
                                 "pid": pid, "tid": tid,
                                 "args": {"name": tname}})

    seg = 0
    pid = seg_pid(0)
    add_process_meta(0, pid)
    cursor = 0.0
    for i, rec in enumerate(records):
        if i in bounds:
            seg += 1
            pid = seg_pid(seg)
            add_process_meta(seg, pid)
            reason = (restarts[seg - 1].get("reason")
                      if seg - 1 < len(restarts) else None)
            trace_events.append({
                "ph": "i", "s": "g", "name": "supervisor restart",
                "ts": _us(cursor), "pid": pid, "tid": 1,
                "args": {"reason": reason or "replay boundary "
                         "(generation numbers went backwards)"},
            })
        gen = rec.get("generation", i)
        wall = max(0.0, float(rec.get("wall_time_s", 0.0) or 0.0))
        trace_events.append({
            "ph": "X", "name": f"gen {gen}", "cat": "generation",
            "ts": _us(cursor), "dur": _us(wall), "pid": pid, "tid": 1,
            "args": {k: rec[k] for k in
                     ("reward_mean", "reward_max", "env_steps", "n_failed")
                     if k in rec},
        })
        if rec.get("env_steps_per_sec") is not None:
            trace_events.append({
                "ph": "C", "name": "env_steps_per_sec",
                "ts": _us(cursor), "pid": pid, "tid": 1,
                "args": {"steps_per_s": float(rec["env_steps_per_sec"])},
            })
        phases = rec.get("phases")
        if isinstance(phases, dict):
            tops = [(n, float(d)) for n, d in phases.items()
                    if isinstance(d, (int, float)) and "/" not in n]
            kids: dict[str, list[tuple[str, float]]] = {}
            for n, d in phases.items():
                if isinstance(d, (int, float)) and "/" in n:
                    parent, _, child = n.partition("/")
                    kids.setdefault(parent, []).append((child, float(d)))
            off = cursor
            for name, dur in tops:
                dur = max(0.0, dur)
                trace_events.append({
                    "ph": "X", "name": name, "cat": "phase",
                    "ts": _us(off), "dur": _us(dur), "pid": pid, "tid": 2,
                })
                k_off = off
                for child, k_dur in kids.get(name, []):
                    k_dur = max(0.0, min(k_dur, dur))
                    trace_events.append({
                        "ph": "X", "name": f"{name}/{child}",
                        "cat": "phase",
                        "ts": _us(k_off), "dur": _us(k_dur),
                        "pid": pid, "tid": 2,
                    })
                    k_off += k_dur
                off += dur
        compiles = rec.get("compile_events")
        if isinstance(compiles, list):
            for e in compiles:
                if not isinstance(e, dict) or "program" not in e:
                    continue
                trace_events.append({
                    "ph": "i", "s": "t",
                    "name": f"compile:{e['program']}", "cat": "compile",
                    "ts": _us(cursor), "pid": pid, "tid": 3,
                    "args": {k: v for k, v in e.items() if k != "program"},
                })
        # ---- async causal lane: flow arrows dispatch → fold/discard ----
        a = rec.get("async")
        if isinstance(a, dict):
            t_end = cursor + wall

            def flow(ph: str, d: int, ts: float) -> dict:
                # one arrow chain per dispatch: Chrome binds flow events
                # by identical (cat, id, name), so the name is the bare
                # dispatch id for every s/t/f of that chain
                ev = {"ph": ph, "id": d, "name": f"d{d}",
                      "cat": "async-flow", "ts": _us(ts),
                      "pid": pid, "tid": 4}
                if ph == "f":
                    ev["bp"] = "e"
                return ev

            for d in a.get("dispatches") or []:
                if not isinstance(d, int) or isinstance(d, bool):
                    continue
                trace_events.append({
                    "ph": "i", "s": "t", "name": f"dispatch d{d}",
                    "cat": "async", "ts": _us(cursor), "pid": pid,
                    "tid": 4, "args": {"dispatch": d},
                })
                trace_events.append(flow("s", d, cursor))
                flow_started.add(d)
            for verb, key in (("fold", "consumed_dispatches"),
                              ("discard", "discarded_dispatches")):
                for d, n in _async_pairs(a, key):
                    if d not in flow_started:
                        # dispatched before this log window: a degenerate
                        # (same-record) arrow still names the causality
                        trace_events.append(flow("s", d, cursor))
                        flow_started.add(d)
                    trace_events.append(flow(
                        "f" if last_touch.get(d) == i else "t", d, t_end))
                    trace_events.append({
                        "ph": "i", "s": "t",
                        "name": f"{verb} d{d}→u{rec.get('generation', i)}",
                        "cat": "async", "ts": _us(t_end), "pid": pid,
                        "tid": 4,
                        "args": {"dispatch": d, "members": n,
                                 "update": rec.get("generation", i),
                                 "what": verb},
                    })
        cursor += wall

    # ---- wall-clock lane: flight-recorder events + heartbeat ----------
    wall_events = [e for e in (events or [])
                   if isinstance(e, dict)
                   and isinstance(e.get("ts"), (int, float))
                   and not isinstance(e.get("ts"), bool)]
    hb_ts = (heartbeat or {}).get("ts")
    hb_placeable = (isinstance(hb_ts, (int, float))
                    and not isinstance(hb_ts, bool))
    # a heartbeat without a numeric ts (hand-edited or foreign file)
    # cannot be placed on the lane — and with no events either, there is
    # no lane to emit at all
    if wall_events or hb_placeable:
        trace_events.append({"ph": "M", "name": "process_name",
                             "pid": _WALL_PID, "tid": 0,
                             "args": {"name": "events (wall clock, "
                                              "rebased — separate clock "
                                              "from the run lanes)"}})
        t0 = min([e["ts"] for e in wall_events]
                 + ([float(hb_ts)] if hb_placeable else []))
        for e in wall_events:
            trace_events.append({
                "ph": "i", "s": "t",
                "name": f"{e.get('kind', 'event')}:{e.get('name', '?')}",
                "ts": _us(e["ts"] - t0), "pid": _WALL_PID, "tid": 1,
                "args": {k: v for k, v in e.items()
                         if k not in ("ts", "kind", "name")},
            })
        if hb_placeable:
            trace_events.append({
                "ph": "i", "s": "t", "name": "last heartbeat",
                "ts": _us(float(hb_ts) - t0),
                "pid": _WALL_PID, "tid": 1,
                "args": {"phase": heartbeat.get("phase"),
                         "generation": heartbeat.get("generation"),
                         "age_s": heartbeat.get("age_s")},
            })

    meta = {}
    if manifest:
        meta = {k: manifest.get(k) for k in
                ("hostname", "pid", "git_sha", "jax") if k in manifest}
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "exporter": "estorch_tpu.obs trace",
            "generations": len(records),
            "segments": len(bounds) + 1,
            "restart_markers": len(bounds),
            **meta,
        },
    }


def validate_trace(trace) -> list[str]:
    """Schema problems in a trace-event dict ([] when clean)."""
    problems: list[str] = []
    if not isinstance(trace, dict):
        return [f"trace is {type(trace).__name__}, not an object"]
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is missing or not a list"]
    for i, e in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where} is not an object")
            continue
        ph = e.get("ph")
        if ph not in TRACE_PHASES:
            problems.append(f"{where} has unknown ph {ph!r}")
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            problems.append(f"{where} has no name")
        if "pid" not in e:
            problems.append(f"{where} has no pid")
        if ph != "M":
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or isinstance(ts, bool) \
                    or ts < 0:
                problems.append(f"{where} has bad ts {ts!r}")
            if "tid" not in e:
                problems.append(f"{where} has no tid")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or isinstance(dur, bool) \
                    or dur < 0:
                problems.append(f"{where} has bad dur {dur!r}")
        if ph == "i" and e.get("s") not in (None, "t", "p", "g"):
            problems.append(f"{where} has bad instant scope {e.get('s')!r}")
        if ph in _FLOW_PHASES:
            fid = e.get("id")
            if not isinstance(fid, int) or isinstance(fid, bool):
                problems.append(f"{where} flow event has bad id {fid!r}")
            if ph == "f" and e.get("bp") not in (None, "e"):
                problems.append(f"{where} flow finish has bad bp "
                                f"{e.get('bp')!r}")
        if "args" in e and not isinstance(e["args"], dict):
            problems.append(f"{where} args is not an object")
    return problems


def write_trace(trace: dict, path: str) -> str:
    """Atomic write (tmp + rename), mirroring the manifest contract."""
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f, default=float)
    os.replace(tmp, path)
    return path
