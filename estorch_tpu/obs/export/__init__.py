"""estorch_tpu.obs.export — operator-facing surfaces over the obs hub.

The hub (spans/counters/heartbeat, PR 2) made single runs explain
themselves; this package makes the signals leave the process
(docs/observability.md, "Export"):

- **prometheus** — zero-dependency Prometheus text exposition encoder +
  validating parser over ``Counters.snapshot()`` and heartbeat
  freshness; served at ``/metrics`` by the serve server and by the
  sidecar;
- **sidecar** — a stdlib-only, jax-free metrics process over a run
  directory (``python -m estorch_tpu.obs serve-metrics --run-dir D``;
  file-runnable on wedged hosts), composing supervisor-published
  cross-restart counter totals with the live child's heartbeat;
- **traceevent** — ``obs trace run.jsonl`` → Perfetto/Chrome
  trace-event JSON: per-generation phase lanes, restart boundaries,
  manifest-keyed process provenance;
- **regress** — ``obs regress`` statistical perf gate: robust medians +
  a learned noise band against committed ``BENCH_*.json`` baselines.

Every module here is importable without jax; prometheus/sidecar/regress
are additionally importable without the package (bench.py and the
sidecar's file-run mode load them by path).
"""

from .prometheus import (GAUGE_NAMES, histogram_series, is_gauge,
                         metric_name, parse_exposition, render_exposition,
                         samples_by_name, validate_histogram_series)
from .regress import (compare, compare_files, compare_tail,
                      compare_tail_files, load_measurement)
from .sidecar import (COUNTERS_FILENAME, MetricsSidecar, compose_hists,
                      compose_totals, publish_counters,
                      read_published_counters)
from .traceevent import export_trace, validate_trace, write_trace

__all__ = [
    "GAUGE_NAMES",
    "is_gauge",
    "metric_name",
    "parse_exposition",
    "render_exposition",
    "samples_by_name",
    "histogram_series",
    "validate_histogram_series",
    "compare",
    "compare_files",
    "compare_tail",
    "compare_tail_files",
    "load_measurement",
    "COUNTERS_FILENAME",
    "MetricsSidecar",
    "compose_totals",
    "compose_hists",
    "publish_counters",
    "read_published_counters",
    "export_trace",
    "validate_trace",
    "write_trace",
]
