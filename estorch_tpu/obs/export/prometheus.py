"""Prometheus text exposition (version 0.0.4) over the obs hub — zero deps.

The hub's numeric facts already exist (``Counters.snapshot()`` rides
every heartbeat), but until now they died inside the process: the serve
server's ``/stats`` is a bespoke JSON blob no scraper understands, and a
training run's counters are only visible to whoever reads its heartbeat
file by hand.  This module turns one counter snapshot (+ optional
heartbeat facts) into the exposition format every Prometheus-compatible
scraper speaks, so fleet dashboards get ES runs for free.

Deliberately stdlib-only and importable WITHOUT the package (the metrics
sidecar loads it by file path, like bench.py loads ``obs/recorder.py``)
— a wedged-jax host must still be scrapeable.

Encoding rules (docs/observability.md "Export"):

* every sample is prefixed ``estorch_`` and sanitized to the metric
  charset (dots and other separators become ``_``);
* the hub's registry is one flat dict, so counter-vs-gauge is decided by
  name: :data:`GAUGE_NAMES` + the ``_last``/``_depth``/``peak_``
  conventions are gauges (last-write-wins), everything else is a
  counter (monotone ``inc``);
* heartbeat facts become ``estorch_heartbeat_age_seconds``,
  ``estorch_heartbeat_generation``, ``estorch_heartbeat_stale`` and an
  ``estorch_heartbeat_info{phase=...,pid=...} 1`` info-style sample;
  ``estorch_up`` is 1 while the watched process beats fresh — the
  alerting primitive;
* label values are escaped per the exposition spec (backslash, quote,
  newline).

:func:`parse_exposition` is the other half: a small validating parser
used by the doctor's export probe and the tests, so "the exposition
parses" is checked by code that did not write it.
"""

from __future__ import annotations

import math
import re

# heartbeat staleness threshold; mirrors obs.recorder.STALE_AFTER_S
# (duplicated literal: this module must import nothing from the package)
DEFAULT_STALE_AFTER_S = 120.0

PREFIX = "estorch_"

# registry names that are gauges (last-write-wins) rather than monotone
# counters — the hub keeps both in one flat dict (obs/counters.py)
GAUGE_NAMES = frozenset({
    "peak_rss_mb",
    "compile_time_s",
    "queue_depth",
    "batch_size_last",
    "bucket_last",
    # cold-start facts (serve/server.py): set once at load / first
    # answer, re-derivable from the compile ledger — gauges
    "startup_s",
    "first_request_s",
    "compiles_at_load",
    "warm_cache_hits",
    # elastic multi-host membership (algo/scheduler.py _HostSource):
    # live-host count is a level, not a monotone count
    "elastic_hosts",
})

_METRIC_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def is_gauge(name: str) -> bool:
    """Counter-vs-gauge classification for one registry name.

    The ``compile_`` prefix covers the compile-ledger facts
    (``compile_s_<program>``, ``compile_peak_bytes_<program>``, … —
    obs/profile/ledger.py): last-write-wins per program, re-derivable
    from the ledger, hence gauges."""
    return (name in GAUGE_NAMES
            or name.endswith(("_last", "_depth"))
            # per-host fold-latency p99s (elastic_fold_p99_s_h<i> +
            # the worst-host rollup): last-write quantile snapshots
            or name.startswith(("peak_", "compile_", "elastic_fold_p99")))


def metric_name(name: str) -> str:
    """Registry name -> exposition metric name (prefixed, sanitized)."""
    clean = _SANITIZE.sub("_", name)
    if not clean or not _METRIC_OK.match(clean):
        clean = "_" + clean
    return PREFIX + clean


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(value: float) -> str:
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _sample(name: str, labels: dict | None, value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape_label(v)}"'
                         for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {_fmt(value)}"
    return f"{name} {_fmt(value)}"


def render_exposition(counters: dict | None,
                      heartbeat: dict | None = None,
                      *,
                      stale_after_s: float = DEFAULT_STALE_AFTER_S,
                      extra_gauges: dict | None = None,
                      up: bool | None = None,
                      histograms: dict | None = None) -> str:
    """One scrape body from a counter snapshot + optional heartbeat facts.

    ``heartbeat`` is the :func:`~estorch_tpu.obs.recorder.read_heartbeat`
    dict (with ``age_s``) or None — None renders ``estorch_up 0`` unless
    ``up`` overrides it (the serve server IS the process being scraped,
    so it is up regardless of whether a heartbeat file is configured).
    ``extra_gauges``: point-in-time facts that live outside the registry
    (queue depth, uptime) — name -> value, rendered as gauges.
    ``histograms``: name → export shape (``Histogram.to_export()``:
    cumulative ``(le, count)`` bucket pairs ending at +Inf, plus sum and
    count) — rendered as true Prometheus ``histogram`` series
    (``_bucket{le=...}``/``_sum``/``_count``), the type whose tails a
    scraper can actually quantile.
    """
    lines: list[str] = []

    def emit(metric: str, mtype: str, help_: str,
             samples: list[tuple[dict | None, float]]) -> None:
        lines.append(f"# HELP {metric} {help_}")
        lines.append(f"# TYPE {metric} {mtype}")
        for labels, value in samples:
            lines.append(_sample(metric, labels, value))

    # an extra gauge SHADOWS a registry entry of the same (sanitized)
    # name: the point-in-time read is fresher than the last-written
    # gauge, and emitting both would duplicate the metric's TYPE — the
    # validating parser rightly rejects that exposition
    extras = {name: value for name, value in (extra_gauges or {}).items()
              if isinstance(value, (int, float))
              and not isinstance(value, bool)}
    shadowed = {metric_name(name) for name in extras}
    for name in sorted(counters or {}):
        value = counters[name]
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        if metric_name(name) in shadowed:
            continue
        mtype = "gauge" if is_gauge(name) else "counter"
        emit(metric_name(name), mtype,
             f"estorch_tpu obs registry {mtype} {name!r}",
             [(None, float(value))])

    for name in sorted(extras):
        emit(metric_name(name), "gauge",
             f"estorch_tpu point-in-time gauge {name!r}",
             [(None, float(extras[name]))])

    for name in sorted(histograms or {}):
        series = histograms[name]
        buckets = series.get("buckets") or []
        if not buckets:
            continue
        base = metric_name(name)
        lines.append(f"# HELP {base} estorch_tpu obs streaming "
                     f"histogram {name!r}")
        lines.append(f"# TYPE {base} histogram")
        for le, cum in buckets:
            lines.append(_sample(f"{base}_bucket", {"le": _fmt(le)},
                                 float(cum)))
        lines.append(_sample(f"{base}_sum", None,
                             float(series.get("sum", 0.0))))
        lines.append(_sample(f"{base}_count", None,
                             float(series.get("count", 0))))

    fresh = False
    if heartbeat is not None:
        age = float(heartbeat.get("age_s", 0.0))
        fresh = age <= stale_after_s
        emit(PREFIX + "heartbeat_age_seconds", "gauge",
             "seconds since the watched process last beat",
             [(None, age)])
        emit(PREFIX + "heartbeat_generation", "gauge",
             "generation in the last heartbeat",
             [(None, float(heartbeat.get("generation", 0) or 0))])
        emit(PREFIX + "heartbeat_stale", "gauge",
             f"1 when the last beat is older than {stale_after_s:.0f}s",
             [(None, 0.0 if fresh else 1.0)])
        emit(PREFIX + "heartbeat_info", "gauge",
             "last-known phase/pid of the watched process",
             [({"phase": str(heartbeat.get("phase", "?")),
                "pid": str(heartbeat.get("pid", "?"))}, 1.0)])
    emit(PREFIX + "up", "gauge",
         "1 while the watched process is alive and beating fresh",
         [(None, 1.0 if (fresh if up is None else up) else 0.0)])
    return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """Validating parser for the text exposition: ``(name, labels,
    value)`` triples.  Raises ``ValueError`` on any malformed line — the
    doctor's export probe treats "parses cleanly" as the health check,
    so this must not silently skip garbage."""
    samples: list[tuple[str, dict, float]] = []
    typed: set[str] = set()
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: bad comment {raw!r}")
            if parts[1] == "TYPE":
                if parts[2] in typed:
                    raise ValueError(
                        f"line {lineno}: duplicate TYPE for {parts[2]}")
                typed.add(parts[2])
                if parts[3] not in ("counter", "gauge", "histogram",
                                    "summary", "untyped"):
                    raise ValueError(
                        f"line {lineno}: unknown type {parts[3]!r}")
            continue
        m = re.match(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$", line)
        if not m:
            raise ValueError(f"line {lineno}: bad sample {raw!r}")
        name, _, labelstr, value = m.groups()
        labels: dict = {}
        if labelstr:
            # the WHOLE block must be well-formed pairs (trailing comma
            # allowed per the exposition spec) — collecting whichever
            # pairs happen to match would bless garbage a real scraper
            # rejects, which is the false health check this validating
            # parser exists to prevent
            pair = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
            if not re.fullmatch(f"{pair}(?:,{pair})*,?", labelstr):
                raise ValueError(f"line {lineno}: bad labels {labelstr!r}")
            for item in re.finditer(
                    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                    labelstr):
                labels[item.group(1)] = item.group(2)
        try:
            v = float(value)
        except ValueError as e:
            raise ValueError(f"line {lineno}: bad value {value!r}") from e
        samples.append((name, labels, v))
    return samples


def samples_by_name(samples: list[tuple[str, dict, float]]) -> dict:
    """Label-free view: name -> value (label-carrying samples keep the
    bare name too; last one wins) — the form the tests and monotonicity
    checks want."""
    return {name: value for name, _labels, value in samples}


def histogram_series(samples: list[tuple[str, dict, float]]) -> dict:
    """Histogram view of parsed samples: ``base name -> {"buckets":
    [(le, cumulative)], "sum", "count"}`` for every base that exposes
    ``_bucket{le=...}`` samples (the inverse of the ``histograms=``
    encoding, so composition checks can read back what they scraped)."""
    out: dict[str, dict] = {}
    for name, labels, value in samples:
        if name.endswith("_bucket") and "le" in labels:
            base = name[: -len("_bucket")]
            le_raw = labels["le"]
            le = math.inf if le_raw == "+Inf" else float(le_raw)
            out.setdefault(base, {"buckets": [], "sum": None,
                                  "count": None})["buckets"].append(
                (le, value))
    for name, labels, value in samples:
        for suffix, key in (("_sum", "sum"), ("_count", "count")):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if base in out and not labels:
                    out[base][key] = value
    return out


def validate_histogram_series(samples: list[tuple[str, dict, float]]
                              ) -> list[str]:
    """Structural problems in the histogram series of a parsed scrape
    ([] when clean): ``le`` edges strictly increasing, cumulative counts
    non-decreasing, a ``+Inf`` bucket present and equal to ``_count``,
    ``_sum``/``_count`` samples present.  The validating half of the
    histogram encoding — used by the doctor's export probe and
    ``obs hist --selfcheck`` so "the tail exports" is checked by code
    that did not write it."""
    problems: list[str] = []
    for base, series in histogram_series(samples).items():
        buckets = series["buckets"]
        les = [le for le, _ in buckets]
        if les != sorted(les) or len(set(les)) != len(les):
            problems.append(f"{base}: le edges not strictly increasing: "
                            f"{les}")
        cums = [c for _, c in buckets]
        if any(b < a for a, b in zip(cums, cums[1:])):
            problems.append(f"{base}: cumulative bucket counts decrease: "
                            f"{cums}")
        if not les or not math.isinf(les[-1]):
            problems.append(f"{base}: no +Inf bucket")
        elif series["count"] is None:
            problems.append(f"{base}: missing _count sample")
        elif cums[-1] != series["count"]:
            problems.append(f"{base}: +Inf bucket {cums[-1]} != _count "
                            f"{series['count']}")
        if series["sum"] is None:
            problems.append(f"{base}: missing _sum sample")
    return problems
