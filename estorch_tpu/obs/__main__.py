"""obs CLI: summarize / trace / profile / regress / hist / serve-metrics
/ collect / dash / autoscale.

Subcommands (docs/observability.md):

  summarize <run.jsonl> [--heartbeat PATH] [--manifest PATH] [--json]
      Per-phase time share, throughput trend, and stall diagnosis for a
      training-run JSONL (the ``train(log_fn=JsonlSink(...))`` output).
      ``--heartbeat`` folds a live run's last-known phase/age into the
      diagnosis.  With no explicit path, a ``heartbeat.json`` next to
      the JSONL is picked up automatically.

  summarize --selfcheck
      Validate the golden record against the record schema (CI gate —
      record-schema drift fails fast here, not in a consumer).

  trace <run.jsonl> [-o trace.json] [--events ring.jsonl]
      Export the run as Perfetto/Chrome trace-event JSON: phase lanes
      per generation, supervisor-restart boundaries marked, process
      lanes keyed by manifest provenance.  ``manifest.json`` /
      ``heartbeat.json`` beside the JSONL are auto-discovered.

  trace --fleet DIR... | --store DIR [-o fleet_trace.json] [--print]
      Distributed-trace assembly (obs/agg/traces.py, docs/
      observability.md "Distributed tracing"): join the fleet's sampled
      per-hop segments (router ``route``/``upstream`` legs, replica
      ``request`` + batcher children) by trace id into one Perfetto
      timeline — per-process lanes, cross-process flow arrows, hedges
      with the loser marked cancelled.  ``--store`` assembles from the
      collector's scraped ``traces-<target>.jsonl`` instead of fleet
      disks.  ``trace --fleet --selfcheck`` is the run_lint.sh gate.

  slow --store DIR [--quantile Q] [--limit N]
      Name the worst stored traces: the stored request histograms carry
      per-bucket trace-id exemplars, so the traces at/above the chosen
      quantile are listed with a per-hop breakdown assembled from the
      store alone (obs/agg/traces.py owns the flags).

  profile <run.jsonl> [--platform auto|cpu|tpu] [--json]
      Per-phase performance attribution (docs/observability.md
      "Profiling"): time share, achieved FLOP/s and bytes/s against the
      platform roofline (v5e bf16 peak on TPU, a measured-GEMM
      calibration on cpu), arithmetic intensity, MFU, and the compile
      ledger with the analytic-vs-XLA cross-check.  Degenerate inputs
      (phase-less records, truncated tail, zero compile events) degrade
      to a noted report — the summarize/trace tolerance contract.
      ``profile --selfcheck`` is the run_lint.sh gate: a synthetic run
      with known FLOPs must produce exactly the expected MFU, and an
      injected 30% eval slowdown must be flagged naming ``eval``.

  regress <current> --baseline <BENCH_*.json> [--label L] [--json]
      Statistical perf gate: robust medians + a noise band learned from
      repeats.  Exit 0 pass, 1 regression.  ``--phases`` gates per-phase
      medians (two run JSONLs) so the verdict names the phase that
      moved; ``--tail [--quantile Q]`` gates an upper quantile (default
      p99) per phase/endpoint with its own learned MAD band — the gate
      for regressions medians can't see; mismatched platforms
      (cpu-fallback artifact vs TPU baseline) are an error, not a
      verdict.  ``regress --selfcheck`` / ``regress --tail --selfcheck``
      are the run_lint.sh gates for the gates.

  hist --selfcheck
      Streaming-histogram math gate (obs/hist.py): exact small-N
      quantiles, known-distribution bucket error bound, merge
      associativity, cross-restart composition + exposition round
      trips.

  serve-metrics --run-dir DIR [--port N] [--port-file PATH]
      Prometheus /metrics sidecar over a run directory (heartbeat +
      supervisor-published counter totals).  On a wedged-jax host run it
      as a file instead: ``python estorch_tpu/obs/export/sidecar.py``.

  collect --targets targets.json --store DIR [--rules rules.json]
      Fleet metrics collector (obs/agg/, docs/observability.md "Fleet
      aggregation"): scrape every configured Prometheus endpoint and
      heartbeat run-dir each tick, land samples in the local time-series
      store, evaluate the declarative SLO/alert rules, and serve the
      collector's own /metrics and /alerts.  ``collect --selfcheck`` is
      the run_lint.sh gate.  Wedged-host file form:
      ``python estorch_tpu/obs/agg/collector.py``.

  dash --store DIR [--once | --watch SECS] [--window S] [--json]
      Terminal fleet console over a collector store: per-target up/down,
      stored-history request/dispatch quantiles, queue depth, recompile
      increase, active alerts, autoscaler desired-vs-actual + decision
      age.  File form: ``python estorch_tpu/obs/agg/dash.py``.

  autoscale --store DIR --capacity capacity.json --fleet-admin H:P
      Autoscaler daemon (obs/agg/autoscale.py, docs/serving.md
      "Autoscaling"): read the collector store + persisted capacity
      model, decide desired replicas via the documented policy, actuate
      the fleet's ``POST /scale``, log every decision append-only;
      ``--replay LOG`` re-derives decisions bit-exactly, ``--selfcheck``
      is the run_lint.sh gate.  Wedged-host file form:
      ``python estorch_tpu/obs/agg/autoscale.py``.

Exit codes: 0 ok; 1 selfcheck problems / unreadable input / regression;
2 bad run dir / bad targets or rules file; 3 bad usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .summarize import (format_summary, load_records_tolerant, selfcheck,
                        summarize)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs",
        description="observability tooling (docs/observability.md)")
    sub = p.add_subparsers(dest="cmd")

    s = sub.add_parser("summarize",
                       help="per-phase share + stall diagnosis of a run")
    s.add_argument("jsonl", nargs="?", default=None,
                   help="run JSONL (one generation record per line)")
    s.add_argument("--heartbeat", default=None, metavar="PATH",
                   help="heartbeat file for live-run stall diagnosis "
                        "(default: heartbeat.json beside the JSONL)")
    s.add_argument("--manifest", default=None, metavar="PATH",
                   help="run manifest for supervisor restart provenance "
                        "and resilience counters (default: manifest.json "
                        "beside the JSONL)")
    s.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable summary on stdout")
    s.add_argument("--selfcheck", action="store_true",
                   help="validate the golden record schema and exit")

    t = sub.add_parser("trace",
                       help="export a run JSONL as Perfetto/Chrome "
                            "trace-event JSON")
    t.add_argument("jsonl", help="run JSONL (one generation per line)")
    t.add_argument("-o", "--out", default=None, metavar="PATH",
                   help="output path (default: trace.json beside the "
                        "JSONL)")
    t.add_argument("--manifest", default=None, metavar="PATH",
                   help="run manifest for restart provenance (default: "
                        "manifest.json beside the JSONL)")
    t.add_argument("--heartbeat", default=None, metavar="PATH",
                   help="heartbeat file (default: heartbeat.json beside "
                        "the JSONL)")
    t.add_argument("--events", default=None, metavar="PATH",
                   help="flight-recorder dump_jsonl file: rendered as a "
                        "wall-clock marker lane")

    pr = sub.add_parser("profile",
                        help="per-phase MFU/roofline attribution of a "
                             "run JSONL")
    pr.add_argument("jsonl", nargs="?", default=None,
                    help="run JSONL (one generation record per line)")
    pr.add_argument("--platform", default="auto",
                    choices=("auto", "cpu", "tpu"),
                    help="roofline platform (auto: manifest.json beside "
                         "the JSONL, else cpu)")
    pr.add_argument("--manifest", default=None, metavar="PATH",
                    help="run manifest for platform auto-detection "
                         "(default: manifest.json beside the JSONL)")
    pr.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable profile on stdout")
    pr.add_argument("--selfcheck", action="store_true",
                    help="prove the attribution math (known FLOPs -> "
                         "known MFU; 30%% eval slowdown localized) and "
                         "exit")

    r = sub.add_parser("regress",
                       help="perf gate: current measurement vs a "
                            "committed baseline")
    r.add_argument("current", nargs="?", default=None,
                   help="run JSONL / bench output to gate")
    r.add_argument("--baseline", default=None, metavar="PATH",
                   help="committed baseline (BENCH_*.json schema, bench "
                        "line, or run JSONL)")
    r.add_argument("--label", default=None,
                   help="filter bench A/B rows by label on both sides")
    r.add_argument("--min-band-pct", type=float, default=None,
                   help="noise-band floor in percent (default 5)")
    r.add_argument("--phases", action="store_true",
                   help="gate per-phase span medians (two run JSONLs) — "
                        "the verdict names the phase that moved")
    r.add_argument("--tail", action="store_true",
                   help="gate an upper quantile (default p99) per "
                        "phase/endpoint with its own learned MAD band — "
                        "flags tail regressions medians can't see, "
                        "naming the quantile and the group")
    r.add_argument("--quantile", type=float, default=None, metavar="Q",
                   help="tail quantile in [0.5, 1) (default 0.99; "
                        "requires --tail)")
    r.add_argument("--json", action="store_true", dest="as_json",
                   help="verdict as one JSON line (default: human line "
                        "+ JSON)")
    r.add_argument("--selfcheck", action="store_true",
                   help="prove the gate flags an injected 30%% slowdown "
                        "and passes an identical run, then exit")

    h = sub.add_parser("hist",
                       help="streaming-histogram tooling (obs/hist.py)")
    h.add_argument("--selfcheck", action="store_true",
                   help="prove the histogram math: known-distribution "
                        "quantile error bound, exact small-N path, merge "
                        "associativity, cross-restart composition round "
                        "trip, exposition round trip")

    m = sub.add_parser("serve-metrics",
                       help="Prometheus /metrics sidecar over a run dir")
    m.add_argument("--run-dir", required=True, metavar="DIR")
    m.add_argument("--host", default="127.0.0.1")
    m.add_argument("--port", type=int, default=9321)
    m.add_argument("--port-file", default=None, metavar="PATH")
    m.add_argument("--stale-after-s", type=float, default=None)

    # collect / dash own their full argparse surfaces (obs/agg/) — the
    # remainder is handed through so the module and file forms accept
    # identical flags
    sub.add_parser("collect", add_help=False,
                   help="fleet metrics collector over targets.json "
                        "(obs/agg/collector.py owns the flags)")
    sub.add_parser("dash", add_help=False,
                   help="terminal fleet console over a collector store "
                        "(obs/agg/dash.py owns the flags)")
    sub.add_parser("autoscale", add_help=False,
                   help="autoscaler daemon: store + capacity model -> "
                        "fleet POST /scale (obs/agg/autoscale.py owns "
                        "the flags)")
    sub.add_parser("slow", add_help=False,
                   help="worst stored traces via histogram exemplars "
                        "(obs/agg/traces.py owns the flags)")
    return p


def _beside(jsonl: str, explicit: str | None, name: str) -> str | None:
    if explicit is not None:
        return explicit
    cand = os.path.join(os.path.dirname(os.path.abspath(jsonl)), name)
    return cand if os.path.exists(cand) else None


def _load_tolerant(jsonl: str) -> list[dict] | None:
    try:
        records, dropped = load_records_tolerant(jsonl)
    except (OSError, ValueError) as e:
        print(f"cannot read {jsonl}: {e}", file=sys.stderr)
        return None
    if dropped:
        print(f"note: dropped a truncated final line in {jsonl} "
              "(crash artifact)", file=sys.stderr)
    return records


def _cmd_summarize(args) -> int:
    if args.selfcheck:
        problems = selfcheck()
        if problems:
            for pr in problems:
                print(f"selfcheck: {pr}", file=sys.stderr)
            return 1
        print("obs selfcheck: OK (record schema + summarize pipeline)")
        return 0

    if not args.jsonl:
        if args.heartbeat:
            # serving processes have no generation JSONL — liveness +
            # serving counters come from the heartbeat alone
            s = summarize([], heartbeat_path=args.heartbeat)
            print(json.dumps(s, default=float) if args.as_json
                  else format_summary(s))
            return 0
        print("summarize needs a run JSONL (or --heartbeat PATH, or "
              "--selfcheck)", file=sys.stderr)
        return 3
    records = _load_tolerant(args.jsonl)
    if records is None:
        return 1
    s = summarize(records,
                  heartbeat_path=_beside(args.jsonl, args.heartbeat,
                                         "heartbeat.json"),
                  manifest_path=_beside(args.jsonl, args.manifest,
                                        "manifest.json"))
    if args.as_json:
        print(json.dumps(s, default=float))
    else:
        print(format_summary(s))
    return 0


def _cmd_trace(args) -> int:
    from .recorder import read_heartbeat
    from .export.traceevent import export_trace, validate_trace, write_trace

    records = _load_tolerant(args.jsonl)
    if records is None:
        return 1
    manifest = None
    mf = _beside(args.jsonl, args.manifest, "manifest.json")
    if mf:
        try:
            with open(mf) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            print(f"note: ignoring unreadable manifest {mf}: {e}",
                  file=sys.stderr)
    hb_path = _beside(args.jsonl, args.heartbeat, "heartbeat.json")
    heartbeat = read_heartbeat(hb_path) if hb_path else None
    events = None
    if args.events:
        try:
            events, dropped = load_records_tolerant(args.events)
            if dropped:
                print(f"note: dropped a truncated final line in "
                      f"{args.events}", file=sys.stderr)
        except (OSError, ValueError) as e:
            print(f"cannot read {args.events}: {e}", file=sys.stderr)
            return 1
    trace = export_trace(records, manifest=manifest, events=events,
                         heartbeat=heartbeat)
    problems = validate_trace(trace)
    if problems:  # exporter bug, not user error — still fail loudly
        for pr in problems:
            print(f"trace: invalid output: {pr}", file=sys.stderr)
        return 1
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(args.jsonl)), "trace.json")
    write_trace(trace, out)
    meta = trace["otherData"]
    print(f"trace: {len(trace['traceEvents'])} events, "
          f"{meta['generations']} generations, "
          f"{meta['segments']} segment(s), "
          f"{meta['restart_markers']} restart marker(s) -> {out}")
    return 0


def _cmd_profile(args) -> int:
    from .profile import (find_cost_model, format_profile, platform_roofline,
                          profile_records)
    from .profile.report import selfcheck as profile_selfcheck

    if args.selfcheck:
        problems = profile_selfcheck()
        if problems:
            for pr in problems:
                print(f"profile selfcheck: {pr}", file=sys.stderr)
            return 1
        print("obs profile selfcheck: OK (known-FLOPs MFU exact, ledger "
              "round-trips the exposition parser, 30% eval slowdown "
              "localized to eval)")
        return 0
    if not args.jsonl:
        print("profile needs a run JSONL (or --selfcheck)", file=sys.stderr)
        return 3
    records = _load_tolerant(args.jsonl)
    if records is None:
        return 1
    platform = args.platform
    if platform == "auto":
        platform = "cpu"
        mf = _beside(args.jsonl, args.manifest, "manifest.json")
        if mf:
            try:
                with open(mf) as f:
                    devs = json.load(f).get("devices") or []
                # the manifest schema (obs/manifest.py) is a LIST of
                # per-device dicts; tolerate a bare dict too
                if isinstance(devs, dict):
                    devs = [devs]
                if any(str(d.get("platform", "")).lower() == "tpu"
                       for d in devs if isinstance(d, dict)):
                    platform = "tpu"
            except (OSError, ValueError) as e:
                print(f"note: ignoring unreadable manifest {mf}: {e}",
                      file=sys.stderr)
    roofline = platform_roofline(platform)
    p = profile_records(records, roofline,
                        cost_model=find_cost_model(records))
    if args.as_json:
        print(json.dumps(p, default=float))
    else:
        print(format_profile(p))
    return 0


def _cmd_regress(args) -> int:
    from .export import regress as _regress

    if args.selfcheck:
        if args.tail:
            problems = _regress.tail_selfcheck()
            if problems:
                for pr in problems:
                    print(f"regress --tail selfcheck: {pr}",
                          file=sys.stderr)
                return 1
            print("obs regress --tail selfcheck: OK (a median-clean "
                  "~2%-of-requests-5x-slower pair passes the median gate "
                  "but is flagged at p99, naming the quantile and the "
                  "endpoint/phase)")
            return 0
        problems = _regress.selfcheck()
        if problems:
            for pr in problems:
                print(f"regress selfcheck: {pr}", file=sys.stderr)
            return 1
        print("obs regress selfcheck: OK (flags a 30% injected slowdown, "
              "passes an identical run)")
        return 0
    if args.quantile is not None and not args.tail:
        print("regress: --quantile only applies to the --tail gate",
              file=sys.stderr)
        return 3
    if not args.current or not args.baseline:
        print("regress needs <current> --baseline PATH (or --selfcheck)",
              file=sys.stderr)
        return 3
    kw = {}
    if args.min_band_pct is not None:
        kw["min_band_pct"] = args.min_band_pct
    if args.tail:
        if args.phases or args.label is not None:
            print("regress: --tail is its own gate — it cannot combine "
                  "with --phases or --label", file=sys.stderr)
            return 3
        if args.quantile is not None:
            kw["quantile"] = args.quantile
        try:
            verdict = _regress.compare_tail_files(args.current,
                                                  args.baseline, **kw)
        except (OSError, ValueError) as e:
            print(f"regress: {e}", file=sys.stderr)
            return 1
        if not args.as_json:
            qn = verdict["quantile"]
            if verdict["regressed_groups"]:
                for name in verdict["regressed_groups"]:
                    row = verdict["groups"][name]
                    print(f"regress: TAIL REGRESSION — {qn} of {name!r} "
                          f"{row['current_q_s']}s vs baseline "
                          f"{row['baseline_q_s']}s (slowdown "
                          f"{row['slowdown_pct']}%, band "
                          f"{row['band_pct']}%, median "
                          f"{row['median_verdict']})")
            else:
                print(f"regress: pass — {qn} of "
                      f"{len(verdict['groups'])} group(s) within their "
                      "learned tail bands")
        print(json.dumps(verdict, default=float))
        return 0 if verdict["verdict"] == "pass" else 1
    if args.phases:
        if args.label is not None:
            # phase records carry no labels — silently ignoring the
            # filter would attribute a verdict to rows the user excluded
            print("regress: --label filters bench A/B rows; --phases "
                  "gates run-JSONL span records, which carry no labels "
                  "— the two cannot combine", file=sys.stderr)
            return 3
        try:
            verdict = _regress.compare_phase_files(args.current,
                                                   args.baseline, **kw)
        except (OSError, ValueError) as e:
            print(f"regress: {e}", file=sys.stderr)
            return 1
        if not args.as_json:
            if verdict["regressed_phases"]:
                for name in verdict["regressed_phases"]:
                    row = verdict["phases"][name]
                    print(f"regress: REGRESSION in phase {name!r} — "
                          f"{row['current_median_s']}s vs baseline "
                          f"{row['baseline_median_s']}s (slowdown "
                          f"{row['slowdown_pct']}%, band "
                          f"{row['band_pct']}%)")
            else:
                print(f"regress: pass — {len(verdict['phases'])} phase(s) "
                      "within their noise bands")
        print(json.dumps(verdict, default=float))
        return 0 if verdict["verdict"] == "pass" else 1
    try:
        verdict = _regress.compare_files(args.current, args.baseline,
                                         label=args.label, **kw)
    except (OSError, ValueError) as e:
        print(f"regress: {e}", file=sys.stderr)
        return 1
    if not args.as_json:
        word = ("REGRESSION" if verdict["verdict"] == "regress"
                else ("pass (improved)" if verdict.get("improved")
                      else "pass"))
        print(f"regress: {word} — {verdict['metric']} "
              f"{verdict['current_median']} vs baseline "
              f"{verdict['baseline_median']} "
              f"(drop {verdict['drop_pct']}%, band {verdict['band_pct']}%)")
    print(json.dumps(verdict, default=float))
    return 0 if verdict["verdict"] == "pass" else 1


def _cmd_hist(args) -> int:
    from . import hist as _hist
    from .export.prometheus import parse_exposition, render_exposition

    if not args.selfcheck:
        print("hist currently has only --selfcheck", file=sys.stderr)
        return 3
    problems = _hist.selfcheck(render=render_exposition,
                               parse=parse_exposition)
    if problems:
        for pr in problems:
            print(f"hist selfcheck: {pr}", file=sys.stderr)
        return 1
    print("obs hist selfcheck: OK (exact small-N quantiles, "
          "known-distribution error bound, merge associativity, "
          "cross-restart composition + exposition round trips)")
    return 0


def _cmd_serve_metrics(args) -> int:
    from .export import sidecar as _sidecar

    argv = ["--run-dir", args.run_dir, "--host", args.host,
            "--port", str(args.port)]
    if args.port_file:
        argv += ["--port-file", args.port_file]
    if args.stale_after_s is not None:
        argv += ["--stale-after-s", str(args.stale_after_s)]
    return _sidecar.main(argv)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # collect/dash delegate whole (obs/agg owns their argparse surface,
    # so the module form and the wedged-host file form accept identical
    # flags) — parsing them here would force every flag to exist twice
    if argv[:1] == ["collect"]:
        from .agg import collector as _collector

        return _collector.main(argv[1:])
    if argv[:1] == ["dash"]:
        from .agg import dash as _dash

        return _dash.main(argv[1:])
    if argv[:1] == ["autoscale"]:
        from .agg import autoscale as _autoscale

        return _autoscale.main(argv[1:])
    if argv[:1] == ["slow"]:
        from .agg import traces as _traces

        return _traces.main_slow(argv[1:])
    if argv[:1] == ["trace"] and any(
            f in argv for f in ("--fleet", "--store", "--selfcheck")):
        # the DISTRIBUTED form (obs/agg owns the flags); the positional
        # run-JSONL export below keeps its surface untouched
        from .agg import traces as _traces

        return _traces.main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.cmd == "summarize":
        return _cmd_summarize(args)
    if args.cmd == "trace":
        return _cmd_trace(args)
    if args.cmd == "profile":
        return _cmd_profile(args)
    if args.cmd == "regress":
        return _cmd_regress(args)
    if args.cmd == "hist":
        return _cmd_hist(args)
    if args.cmd == "serve-metrics":
        return _cmd_serve_metrics(args)
    build_parser().print_help()
    return 3


if __name__ == "__main__":
    sys.exit(main())
