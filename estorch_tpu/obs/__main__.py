"""obs CLI: ``python -m estorch_tpu.obs summarize <run.jsonl>``.

Subcommands:

  summarize <run.jsonl> [--heartbeat PATH] [--json]
      Per-phase time share, throughput trend, and stall diagnosis for a
      training-run JSONL (the ``train(log_fn=JsonlSink(...))`` output).
      ``--heartbeat`` folds a live run's last-known phase/age into the
      diagnosis.  With no explicit path, a ``heartbeat.json`` next to
      the JSONL is picked up automatically.

  summarize --selfcheck
      Validate the golden record against the record schema (CI gate —
      record-schema drift fails fast here, not in a consumer).

Exit codes: 0 ok; 1 selfcheck problems / unreadable input; 3 bad usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .summarize import format_summary, load_records, selfcheck, summarize


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs",
        description="observability tooling (docs/observability.md)")
    sub = p.add_subparsers(dest="cmd")
    s = sub.add_parser("summarize",
                       help="per-phase share + stall diagnosis of a run")
    s.add_argument("jsonl", nargs="?", default=None,
                   help="run JSONL (one generation record per line)")
    s.add_argument("--heartbeat", default=None, metavar="PATH",
                   help="heartbeat file for live-run stall diagnosis "
                        "(default: heartbeat.json beside the JSONL)")
    s.add_argument("--manifest", default=None, metavar="PATH",
                   help="run manifest for supervisor restart provenance "
                        "and resilience counters (default: manifest.json "
                        "beside the JSONL)")
    s.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable summary on stdout")
    s.add_argument("--selfcheck", action="store_true",
                   help="validate the golden record schema and exit")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd != "summarize":
        build_parser().print_help()
        return 3

    if args.selfcheck:
        problems = selfcheck()
        if problems:
            for pr in problems:
                print(f"selfcheck: {pr}", file=sys.stderr)
            return 1
        print("obs selfcheck: OK (record schema + summarize pipeline)")
        return 0

    if not args.jsonl:
        if args.heartbeat:
            # serving processes have no generation JSONL — liveness +
            # serving counters come from the heartbeat alone
            s = summarize([], heartbeat_path=args.heartbeat)
            print(json.dumps(s, default=float) if args.as_json
                  else format_summary(s))
            return 0
        print("summarize needs a run JSONL (or --heartbeat PATH, or "
              "--selfcheck)", file=sys.stderr)
        return 3
    try:
        records = load_records(args.jsonl)
    except (OSError, ValueError) as e:
        print(f"cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 1
    run_dir = os.path.dirname(os.path.abspath(args.jsonl))
    hb = args.heartbeat
    if hb is None:
        cand = os.path.join(run_dir, "heartbeat.json")
        hb = cand if os.path.exists(cand) else None
    mf = args.manifest
    if mf is None:
        cand = os.path.join(run_dir, "manifest.json")
        mf = cand if os.path.exists(cand) else None
    s = summarize(records, heartbeat_path=hb, manifest_path=mf)
    if args.as_json:
        print(json.dumps(s, default=float))
    else:
        print(format_summary(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
