"""Distributed tracing: per-process trace segments + tail-based sampling.

Every serving hop already carries a causal id — the router mints and
forwards ``X-Trace-Id``, the server honors it, the batcher's
``batch_dispatch`` events list member ids — but each process keeps its
spans to itself, so a p99 breach flagged by the collector cannot say
WHICH request was slow or WHERE (router retry? hedge loss? queue wait?).
This module is the per-process half of the answer; ``obs/agg/traces.py``
is the assembly half.

Segment schema (one JSON object per line in ``<run_dir>/traces.jsonl``)::

    {"trace_id", "span_id", "parent_span_id", "proc", "name",
     "t0_mono", "dur_s", "ts", "seq", "attrs"}

``t0_mono`` is the process-local ``perf_counter`` start (exact intra-
process arithmetic); ``ts`` is the wall-clock start (the cross-process
alignment key — per-host monotonic clocks share no epoch).  ``seq`` is a
per-process monotonic cursor assigned when the sampler KEEPS the trace,
which is what makes the ``/traces?since=<seq>`` scrape endpoint
idempotent.  Parent span ids cross process boundaries in the
``X-Parent-Span`` header beside ``X-Trace-Id``; a hop that already knows
the trace is interesting (retry legs, hedge legs) forces the downstream
sampler via ``X-Trace-Sampled: 1``.

Tail-based sampling (:class:`TraceSampler`): the keep/drop decision is
made at trace END on each process, so the sampler can keep exactly the
traces worth keeping — every error / shed / retried / hedged /
breaker-touched trace, every trace slower than the live p99 of the
configured request histogram (read off the telemetry hub), and a
deterministic 1-in-N head-sampled baseline (``crc32(trace_id) % N``, so
every hop of a head-sampled trace keeps it WITHOUT coordination).
Everything else is dropped at the ring; ``traces_sampled`` /
``traces_dropped`` counters measure the shed.

Deliberately stdlib-only, jax-free, and importable WITHOUT the package
(router.py / server.py file-load it beside themselves) — the sidecar
discipline: tracing must outlive a wedged jax host.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib

TRACING_SCHEMA = 1
TRACE_HEADER = "X-Trace-Id"
PARENT_SPAN_HEADER = "X-Parent-Span"
SAMPLED_HEADER = "X-Trace-Sampled"
TRACES_FILENAME = "traces.jsonl"

# sampler defaults: 1-in-16 head baseline, p99 rule armed once the live
# histogram holds enough mass to make its tail meaningful
DEFAULT_HEAD_EVERY = 16
DEFAULT_P99_MIN_COUNT = 64

_SEGMENT_KEYS = ("trace_id", "span_id", "proc", "name")


def head_sampled(trace_id: str, head_every: int = DEFAULT_HEAD_EVERY) -> bool:
    """Deterministic 1-in-N head sample on the trace id alone — every
    process reaches the same verdict for the same trace with zero
    coordination, so baseline traces assemble COMPLETE."""
    if head_every <= 1:
        return True
    return zlib.crc32(trace_id.encode()) % int(head_every) == 0


def make_segment(trace_id: str, span_id: str, parent_span_id: str | None,
                 proc: str, name: str, t0_mono: float, dur_s: float,
                 attrs: dict | None = None,
                 ts: float | None = None) -> dict:
    """One structured span segment (see module docstring).  ``ts``
    defaults to now minus the duration — callers record at span end."""
    return {
        "trace_id": str(trace_id),
        "span_id": str(span_id),
        "parent_span_id": str(parent_span_id) if parent_span_id else None,
        "proc": str(proc),
        "name": str(name),
        "t0_mono": float(t0_mono),
        "dur_s": max(0.0, float(dur_s)),
        "ts": float(ts) if ts is not None
        else time.time() - max(0.0, float(dur_s)),
        "attrs": dict(attrs or {}),
    }


def valid_segment(row) -> bool:
    """Is ``row`` a well-formed segment?  Readers (assembly, the
    collector) must skip foreign/torn lines, never choke on them."""
    if not isinstance(row, dict):
        return False
    for k in _SEGMENT_KEYS:
        if not isinstance(row.get(k), str) or not row[k]:
            return False
    for k in ("dur_s", "ts"):
        v = row.get(k)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return False
    return True


def read_segments(path: str) -> list[dict]:
    """Segments from one ``traces.jsonl``, torn-tail / garbage tolerant
    (post-mortem inputs degrade, never crash)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return []
    out: list[dict] = []
    for ln in text.splitlines():
        if not ln.strip():
            continue
        try:
            row = json.loads(ln)
        except ValueError:
            continue  # torn tail or foreign line
        if valid_segment(row):
            out.append(row)
    return out


class TraceSampler:
    """Tail-based keep/drop policy, decided at trace end (see module
    docstring).  ``hists`` is the hub's histogram registry (duck-typed:
    ``.get(name)`` → histogram with ``.count`` / ``.quantile(q)``) and
    may be None — the p99 rule simply stays disarmed."""

    def __init__(self, *, hists=None, hist_name: str | None = None,
                 head_every: int = DEFAULT_HEAD_EVERY,
                 p99_min_count: int = DEFAULT_P99_MIN_COUNT):
        self.hists = hists
        self.hist_name = hist_name
        self.head_every = int(head_every)
        self.p99_min_count = int(p99_min_count)

    def verdict(self, trace_id: str, dur_s: float | None = None, *,
                error: bool = False, shed: bool = False,
                retried: bool = False, hedged: bool = False,
                breaker: bool = False, forced: bool = False) -> str | None:
        """The keep REASON, or None to drop."""
        if forced:
            return "forced"
        if error:
            return "error"
        if shed:
            return "shed"
        if retried:
            return "retry"
        if hedged:
            return "hedge"
        if breaker:
            return "breaker"
        if dur_s is not None and self.hists is not None and self.hist_name:
            h = self.hists.get(self.hist_name)
            if h is not None and h.count >= self.p99_min_count:
                p99 = h.quantile(0.99)
                if p99 == p99 and float(dur_s) > p99:  # NaN-safe
                    return "p99"
        if head_sampled(trace_id, self.head_every):
            return "head"
        return None


class ProcessTracer:
    """Per-process segment buffer + sampler + atomic flush.

    Lifecycle: hops :meth:`add` segments as spans end (buffered per
    trace id — the keep/drop decision is TAIL-based), then :meth:`finish`
    the trace with its outcome flags; kept segments get a ``seq`` cursor
    and enter both the flush ring and the bounded ``recent`` window the
    ``/traces?since=`` endpoint serves.  :meth:`record` bypasses the
    sampler for spans that are per-dispatch rather than per-request (the
    batcher's ``batch`` span — one per coalesced dispatch, already
    bounded by construction).

    Thread-safe throughout: the router finishes traces from concurrent
    handler threads, and hedged attempts add segments from their racer
    threads.
    """

    def __init__(self, proc: str, *, counters=None, hists=None,
                 hist_name: str | None = None,
                 head_every: int = DEFAULT_HEAD_EVERY,
                 p99_min_count: int = DEFAULT_P99_MIN_COUNT,
                 path: str | None = None,
                 capacity: int = 4096,
                 recent_capacity: int = 4096,
                 max_pending: int = 512,
                 max_file_lines: int = 20000,
                 flush_every: int = 64):
        self.proc = str(proc)
        self.counters = counters
        self.path = os.path.abspath(path) if path else None
        self.sampler = TraceSampler(hists=hists, hist_name=hist_name,
                                    head_every=head_every,
                                    p99_min_count=p99_min_count)
        self.max_pending = int(max_pending)
        self.max_file_lines = int(max_file_lines)
        self.flush_every = int(flush_every)
        self._lock = threading.Lock()
        self._seq = 0
        self._span_seq = 0
        # pending: trace id → buffered segments awaiting the tail verdict
        self._pending: collections.OrderedDict[str, list[dict]] = \
            collections.OrderedDict()
        # decided: trace id → keep reason (or None = dropped), bounded.
        # A segment can arrive AFTER the verdict — a cancelled hedge
        # loser's leg lands when its aborted socket finally raises — and
        # must follow its trace's fate, not reopen a pending entry that
        # nobody will ever finish.
        self._decided: collections.OrderedDict[str, str | None] = \
            collections.OrderedDict()
        self._max_decided = 1024
        # ring: kept segments not yet flushed to disk (oldest evicted)
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=int(capacity))
        # recent: kept segments the /traces?since= endpoint serves
        self._recent: collections.deque[dict] = collections.deque(
            maxlen=int(recent_capacity))

    # ------------------------------------------------------------- spans

    def span_id(self) -> str:
        """Mint one process-unique span id."""
        with self._lock:
            self._span_seq += 1
            return f"{self.proc}.{self._span_seq}"

    def add(self, segment: dict) -> None:
        """Buffer one finished span under its trace id, pending the tail
        verdict.  Overflowing the pending table drops the OLDEST trace
        (counted) — a hop that never finishes must not grow memory."""
        with self._lock:
            tid = segment["trace_id"]
            if tid in self._decided:
                # late segment for an already-judged trace: follow the
                # verdict (kept traces get the straggler leg, dropped
                # traces stay dropped)
                if self._decided[tid] is not None:
                    self._keep_locked([segment])
                return
            buf = self._pending.get(tid)
            if buf is None:
                while len(self._pending) >= self.max_pending:
                    self._pending.popitem(last=False)
                    self._inc("traces_dropped")
                buf = self._pending[tid] = []
            buf.append(segment)

    def record(self, segment: dict) -> None:
        """Keep one segment unconditionally (no per-trace buffering) —
        for per-dispatch spans like the batcher's ``batch``."""
        with self._lock:
            self._keep_locked([segment])

    def finish(self, trace_id: str, dur_s: float | None = None, *,
               error: bool = False, shed: bool = False,
               retried: bool = False, hedged: bool = False,
               breaker: bool = False, forced: bool = False) -> bool:
        """Apply the tail verdict to the trace's buffered segments.
        Returns True when kept (callers propagate it as
        ``X-Trace-Sampled`` on response headers if they care)."""
        reason = self.sampler.verdict(
            trace_id, dur_s, error=error, shed=shed, retried=retried,
            hedged=hedged, breaker=breaker, forced=forced)
        with self._lock:
            segs = self._pending.pop(trace_id, None) or []
            self._decided[trace_id] = reason
            while len(self._decided) > self._max_decided:
                self._decided.popitem(last=False)
            if reason is None:
                self._inc("traces_dropped")
                return False
            roots = [s for s in segs if not s.get("parent_span_id")]
            for s in roots or segs[:1]:
                s["attrs"]["sampled"] = reason
            self._keep_locked(segs)
            self._inc("traces_sampled")
        if self.path and len(self._ring) >= self.flush_every:
            self.flush()
        return True

    def _keep_locked(self, segs: list[dict]) -> None:
        for s in segs:
            self._seq += 1
            s["seq"] = self._seq
            self._ring.append(s)
            self._recent.append(s)

    def _inc(self, name: str) -> None:
        if self.counters is not None:
            self.counters.inc(name)

    # ------------------------------------------------------------- flush

    def flush(self) -> int:
        """Append the ring to ``traces.jsonl`` atomically and drain it.

        Same contract as the flight recorder's ``dump_jsonl``: stage the
        existing file into ``.tmp`` (dropping a torn tail), append the
        ring, ``os.replace`` — a crash leaves the previous or the new
        complete file, never a truncated one.  The retained tail is
        capped at ``max_file_lines`` so disk stays bounded by
        construction."""
        if not self.path:
            return 0
        with self._lock:
            segs = list(self._ring)
            self._ring.clear()
        if not segs:
            return 0
        with self._lock:  # serialize concurrent flushers on the file
            prev_lines: list[str] = []
            if os.path.exists(self.path):
                try:
                    with open(self.path) as old:
                        prev = old.read()
                except OSError:
                    prev = ""
                if prev and not prev.endswith("\n"):
                    cut = prev.rfind("\n")
                    prev = prev[:cut + 1] if cut >= 0 else ""
                prev_lines = prev.splitlines()
            keep_prev = max(0, self.max_file_lines - len(segs))
            prev_lines = prev_lines[-keep_prev:] if keep_prev else []
            tmp = self.path + ".tmp"
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                for ln in prev_lines:
                    f.write(ln + "\n")
                for s in segs:
                    f.write(json.dumps(s, default=float) + "\n")
            os.replace(tmp, self.path)
        return len(segs)

    # --------------------------------------------------------- scraping

    def since(self, cursor: int) -> tuple[list[dict], int]:
        """Kept segments with ``seq > cursor`` (bounded by the recent
        window) plus the new cursor — the ``/traces?since=`` payload."""
        cursor = int(cursor)
        with self._lock:
            segs = [s for s in self._recent if s.get("seq", 0) > cursor]
            top = self._seq
        return segs, top


def traces_payload(tracer: ProcessTracer | None, since: int,
                   hists=None) -> dict:
    """The ``/traces?since=`` response body: new segments + cursor +
    the hub's histogram bucket exemplars (how trace ids reach the
    collector's store without widening the Prometheus text format)."""
    if tracer is None:
        return {"proc": None, "segments": [], "cursor": int(since),
                "exemplars": {}}
    segs, cursor = tracer.since(since)
    exemplars: dict[str, dict] = {}
    if hists is not None:
        try:
            for name, snap in hists.snapshot(compact=True).items():
                ex = snap.get("exemplars")
                if ex:
                    exemplars[name] = ex
        except Exception:  # noqa: BLE001 — scrape answers degrade, not 500
            exemplars = {}
    return {"proc": tracer.proc, "segments": segs, "cursor": cursor,
            "exemplars": exemplars}
