"""estorch_tpu.obs — first-class observability for ES runs.

Production ES is operationally opaque by default: a generation is one
fused device program, a wedge surfaces as a supervisor timeout, and a
regression shows up as a single slower steps/s number with no phase
attribution.  This package makes every run, wedge, and regression
explain itself (docs/observability.md):

- **spans** (`spans.py`): per-phase timers (sample/eval/update/...) with
  ``block_until_ready`` fencing, merged into each generation record;
- **counters/gauges** (`counters.py`): recompiles, env-steps, rollout
  failures, peak RSS — one snapshot per run;
- **flight recorder + heartbeat** (`recorder.py`): ring buffer of recent
  spans/events + an atomically-rewritten last-known-state file that
  bench.py, tpu_watch, and doctor read when a run stops answering;
- **sinks** (`sinks.py`): JSONL / TensorBoard / fan-out record writers
  (absorbed from ``utils.metrics``; old names still importable there);
- **manifest** (`manifest.py`): config + jax version + device topology +
  git sha, written once per run;
- **summarize** (`summarize.py`, ``python -m estorch_tpu.obs``): phase
  time share, throughput trend, stall diagnosis from a run JSONL;
- **export** (`export/`): the operator-facing surfaces — Prometheus
  text exposition (+ the jax-free ``serve-metrics`` sidecar), Perfetto
  trace-event export (``obs trace``), and the ``obs regress`` perf gate
  over committed ``BENCH_*.json`` baselines.

``utils.metrics`` and ``utils.profiler`` remain as re-export shims for
backward compatibility.
"""

from . import export  # noqa: F401  (prometheus/sidecar/trace/regress)
from .counters import Counters, NullCounters
from .export import (MetricsSidecar, export_trace, parse_exposition,
                     render_exposition, validate_trace)
from .hist import Histogram, Histograms, NullHistograms
from .manifest import collect_manifest, load_manifest, write_manifest
from .recorder import (HEARTBEAT_ENV, STALE_AFTER_S, FlightRecorder,
                       Heartbeat, describe_heartbeat, read_heartbeat)
from .sinks import (JsonlSink, JsonlWriter, MultiSink, MultiWriter,
                    TensorBoardSink, TensorBoardWriter)
from .spans import NULL_TELEMETRY, Telemetry, resolve_telemetry
from .summarize import (format_summary, load_records,
                        load_records_tolerant, selfcheck, summarize,
                        validate_record)
from .trace import annotate, timed_generations, trace

__all__ = [
    "Counters",
    "NullCounters",
    "Histogram",
    "Histograms",
    "NullHistograms",
    "FlightRecorder",
    "Heartbeat",
    "HEARTBEAT_ENV",
    "STALE_AFTER_S",
    "describe_heartbeat",
    "read_heartbeat",
    "JsonlSink",
    "JsonlWriter",
    "MultiSink",
    "MultiWriter",
    "TensorBoardSink",
    "TensorBoardWriter",
    "Telemetry",
    "NULL_TELEMETRY",
    "resolve_telemetry",
    "collect_manifest",
    "write_manifest",
    "load_manifest",
    "format_summary",
    "load_records",
    "load_records_tolerant",
    "export",
    "MetricsSidecar",
    "export_trace",
    "validate_trace",
    "parse_exposition",
    "render_exposition",
    "selfcheck",
    "summarize",
    "validate_record",
    "annotate",
    "timed_generations",
    "trace",
]
