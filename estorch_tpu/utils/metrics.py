"""Backward-compat shim: the metrics writers moved to
:mod:`estorch_tpu.obs.sinks` (the observability subsystem,
docs/observability.md).  Import from ``estorch_tpu.obs`` in new code;
this module keeps the historical ``utils.metrics`` surface alive.
"""

from __future__ import annotations

from ..obs.sinks import (JsonlSink, JsonlWriter, MultiSink,  # noqa: F401
                         MultiWriter, TensorBoardSink, TensorBoardWriter)

__all__ = ["JsonlWriter", "TensorBoardWriter", "MultiWriter",
           "JsonlSink", "TensorBoardSink", "MultiSink"]
