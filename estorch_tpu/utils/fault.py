"""Fault tolerance: straggler/failure-tolerant ES updates.

The reference hangs forever if one worker dies mid-gather (SURVEY.md §5
'Failure detection').  ES is uniquely forgiving: the estimator is a mean
over population members, so a failed host's slice can simply be DROPPED and
the weights renormalized — an unbiased estimate from the survivors.  Two
layers here:

1. ``mask_and_renormalize(weights, valid)`` — zero failed members' weights
   and rescale so the effective population matches the actual contributor
   count.  Works for both backends (the psum update is linear in weights).
2. Host-side failure capture: HostEngine marks members whose rollout raised
   as invalid (NaN fitness) instead of crashing the generation;
   ``valid_mask(fitness)`` converts that to the mask for (1).

Recovery from full-process failure is the checkpoint path
(utils/checkpoint.py): generations are stateless given (params, key,
generation), so resume == reload + rerun.
"""

from __future__ import annotations

import numpy as np


def valid_mask(fitness: np.ndarray) -> np.ndarray:
    """Members whose evaluation produced a usable fitness."""
    return np.isfinite(np.asarray(fitness))


def mask_and_renormalize(weights: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """Zero invalid members and rescale survivors by n/valid_count.

    The ES update divides by the STATIC population size n inside the engine;
    multiplying surviving weights by n/n_valid makes the estimate the mean
    over actual contributors — the straggler-drop scheme of SURVEY.md §5.
    Raises if fewer than 2 members survived (no rankable population).
    """
    weights = np.asarray(weights, dtype=np.float32)
    valid = np.asarray(valid, dtype=bool)
    n = weights.shape[0]
    n_valid = int(valid.sum())
    if n_valid < 2:
        raise RuntimeError(
            f"only {n_valid}/{n} population members produced valid fitness — "
            "cannot form an update; check env/rollout health"
        )
    out = np.where(valid, weights, 0.0).astype(np.float32)
    return out * (n / n_valid)


def rank_weights_with_failures(fitness: np.ndarray) -> np.ndarray:
    """Centered ranks over the VALID members only, failures zero-weighted.

    Invalid members neither push nor pull the update; valid members are
    ranked among themselves and renormalized.
    """
    from ..ops.ranks import centered_rank_np

    fitness = np.asarray(fitness)
    valid = valid_mask(fitness)
    n = fitness.shape[0]
    if valid.all():
        return centered_rank_np(fitness)
    ranks = np.zeros(n, dtype=np.float32)
    sub = centered_rank_np(fitness[valid])
    ranks[valid] = sub
    return mask_and_renormalize(ranks, valid)
