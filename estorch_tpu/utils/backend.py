"""Backend selection helpers for this image's quirky device setup.

The axon sitecustomize pins ``JAX_PLATFORMS=axon`` and its get_backend
override ignores the env var, so the only reliable way to run on CPU (for
virtual-device sharding tests, dry runs, or tunnel-outage fallbacks) is an
in-process config update BEFORE first device use.
"""

from __future__ import annotations


def force_cpu_backend(n_devices: int = 8) -> bool:
    """Best-effort switch to the CPU backend with ``n_devices`` virtual
    devices.  Returns True if the config took; False if the backend was
    already initialized (caller proceeds with whatever is live)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", max(int(n_devices), 1))
        return True
    except Exception:
        return False
