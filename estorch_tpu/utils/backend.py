"""Backend selection helpers for this image's quirky device setup.

The axon sitecustomize pins ``JAX_PLATFORMS=axon`` and its get_backend
override ignores the env var, so the only reliable way to run on CPU (for
virtual-device sharding tests, dry runs, or tunnel-outage fallbacks) is an
in-process config update BEFORE first device use.
"""

from __future__ import annotations


def default_compilation_cache_dir() -> str:
    """The cache location :func:`enable_compilation_cache` uses when no
    directory is given (shared with the doctor's report)."""
    import os

    return os.path.join(
        os.path.expanduser("~"), ".cache", "estorch_tpu", "xla_cache"
    )


def enable_compilation_cache(
    cache_dir: str | None = None, min_compile_time_s: float = 1.0
) -> str:
    """Turn on XLA's persistent compilation cache and return the directory.

    A fresh process pays 20-40s of XLA compile for the fused generation
    program before the first update (BENCHMARKS.md).  The reference never
    had this cost (eager torch), so hiding it is part of matching its
    interactive feel: with the persistent cache, every process after the
    first loads the compiled executable from disk in well under a second —
    across bench stages, example scripts, pool workers, and restarts after
    a crash (the checkpoint/resume story's missing half).

    ``min_compile_time_s`` gates which programs are worth persisting
    (default 1s — the tiny host-side jits stay out of the cache).  Safe to
    call before OR after backend init, and re-callable with a new
    directory: JAX pins its cache object on first use and never re-reads
    the dir config, so a dir change must also reset the live cache (done
    here) or it would silently keep using the old path.

    CPU caveat: XLA:CPU AOT entries record exact machine features; the
    loader logs noisy E-level feature-mismatch warnings (observed even
    same-machine for XLA-internal pseudo-features like
    ``+prefer-no-scatter``) and a cache shared ACROSS heterogeneous CPUs
    could in principle hit SIGILL — keep the cache directory per-machine.
    TPU executables key on the chip generation and have no such edge.
    """
    import os

    import jax

    if cache_dir is None:
        cache_dir = default_compilation_cache_dir()
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_time_s)
    )
    # -1: no size floor AND no filesystem-specific override (the default 0
    # permits an override that can skip small entries on some filesystems)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _reset_live_cache()
    return cache_dir


def _reset_live_cache() -> None:
    """Drop JAX's already-initialized persistent-cache object (if any) so
    the dir config takes effect; harmless when nothing was initialized."""
    try:
        from jax.experimental.compilation_cache import compilation_cache as cc

        cc.reset_cache()
    except Exception:
        pass


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: new jax exposes ``jax.shard_map``
    (replication check kwarg ``check_vma``); 0.4.x only has
    ``jax.experimental.shard_map.shard_map`` with the same check spelled
    ``check_rep``.  Single shim so call sites never branch on version."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def set_host_device_count_flag(n_devices: int) -> None:
    """Set (or REPLACE) ``--xla_force_host_platform_device_count`` in
    ``XLA_FLAGS`` — the version-portable spelling of
    ``jax_num_cpu_devices``.  Only effective before the backend
    initializes.  Replacing an existing value matters: inheriting a
    different count from the environment silently changes the mesh the
    8-device sharding tests assert on."""
    import os
    import re

    n = max(int(n_devices), 1)
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags


def force_cpu_backend(n_devices: int = 8) -> bool:
    """Best-effort switch to the CPU backend with ``n_devices`` virtual
    devices.  Returns True if the config took; False if the backend was
    already initialized (caller proceeds with whatever is live)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return False
    try:
        jax.config.update("jax_num_cpu_devices", max(int(n_devices), 1))
    except AttributeError:
        # older jax has no jax_num_cpu_devices; importing jax does not
        # initialize a backend, so the env flag still takes effect here
        set_host_device_count_flag(n_devices)
    except Exception:
        return False
    return True
