"""Backend selection helpers for this image's quirky device setup.

The axon sitecustomize pins ``JAX_PLATFORMS=axon`` and its get_backend
override ignores the env var, so the only reliable way to run on CPU (for
virtual-device sharding tests, dry runs, or tunnel-outage fallbacks) is an
in-process config update BEFORE first device use.
"""

from __future__ import annotations


def default_compilation_cache_dir() -> str:
    """The cache location :func:`enable_compilation_cache` uses when no
    directory is given (shared with the doctor's report)."""
    import os

    return os.path.join(
        os.path.expanduser("~"), ".cache", "estorch_tpu", "xla_cache"
    )


def enable_compilation_cache(
    cache_dir: str | None = None, min_compile_time_s: float = 1.0
) -> str:
    """Turn on XLA's persistent compilation cache and return the directory.

    A fresh process pays 20-40s of XLA compile for the fused generation
    program before the first update (BENCHMARKS.md).  The reference never
    had this cost (eager torch), so hiding it is part of matching its
    interactive feel: with the persistent cache, every process after the
    first loads the compiled executable from disk in well under a second —
    across bench stages, example scripts, pool workers, and restarts after
    a crash (the checkpoint/resume story's missing half).

    ``min_compile_time_s`` gates which programs are worth persisting
    (default 1s — the tiny host-side jits stay out of the cache).  Safe to
    call before OR after backend init, and re-callable with a new
    directory: JAX pins its cache object on first use and never re-reads
    the dir config, so a dir change must also reset the live cache (done
    here) or it would silently keep using the old path.

    CPU caveat: XLA:CPU AOT entries record exact machine features; the
    loader logs noisy E-level feature-mismatch warnings (observed even
    same-machine for XLA-internal pseudo-features like
    ``+prefer-no-scatter``) and a cache shared ACROSS heterogeneous CPUs
    could in principle hit SIGILL — keep the cache directory per-machine.
    TPU executables key on the chip generation and have no such edge.
    """
    import os

    import jax

    if cache_dir is None:
        cache_dir = default_compilation_cache_dir()
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_time_s)
    )
    # -1: no size floor AND no filesystem-specific override (the default 0
    # permits an override that can skip small entries on some filesystems)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _disable_path_dependent_cache_keys()
    _reset_live_cache()
    return cache_dir


def _disable_path_dependent_cache_keys() -> None:
    """Keep cache keys independent of the cache DIRECTORY's path.

    With the persistent cache enabled, jax (0.4.36+) default-enables
    auxiliary XLA caches whose path — derived from the cache dir — lands
    in ``debug_options`` and is hashed into every cache key
    (``xla_gpu_per_fusion_autotune_cache_dir`` is not on the cache-key
    sanitizer's clear list).  That makes entries non-portable: a warm
    bundle's programs (compiled under ``<bundle>/warm``) could never hit
    from the serving process's cache dir.  The auxiliary caches are
    GPU-only machinery (fusion autotuning), nothing lost on cpu/tpu."""
    import jax

    if hasattr(jax.config, "jax_persistent_cache_enable_xla_caches"):
        jax.config.update("jax_persistent_cache_enable_xla_caches", "")
    # else: older jax — no auxiliary caches, keys were already portable


def _reset_live_cache() -> None:
    """Drop JAX's already-initialized persistent-cache object (if any) so
    the dir config takes effect; harmless when nothing was initialized."""
    try:
        from jax.experimental.compilation_cache import compilation_cache as cc

        cc.reset_cache()
    except Exception:
        pass


def current_compilation_cache_dir() -> str | None:
    """The persistent-cache directory this process is configured with, or
    None when the cache is disabled (the default outside conftest)."""
    import jax

    try:
        return jax.config.jax_compilation_cache_dir or None
    except AttributeError:
        return None


def scoped_compilation_cache(cache_dir: str, min_compile_time_s: float = 0.0):
    """Context manager: redirect the persistent XLA compilation cache to
    ``cache_dir`` for the duration of the block, then restore the prior
    configuration (including "disabled").

    ``min_compile_time_s=0`` persists EVERY program compiled inside the
    block — the warm-bundle export wants the tiny auxiliary programs
    (``convert_element_type``, ``broadcast_in_dim``, …) too, because a
    "zero fresh builds at load" proof fails on any program left out.
    Process-global (jax config is), so don't run concurrent exports.
    """
    import contextlib
    import os

    import jax

    @contextlib.contextmanager
    def _scope():
        prior_dir = current_compilation_cache_dir()
        prior_min = jax.config.jax_persistent_cache_min_compile_time_secs
        prior_size = jax.config.jax_persistent_cache_min_entry_size_bytes
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # portability is the POINT of the warm export: keys must not
        # depend on where the cache dir happens to live
        _disable_path_dependent_cache_keys()
        _reset_live_cache()
        try:
            yield cache_dir
        finally:
            jax.config.update("jax_compilation_cache_dir", prior_dir or "")
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", prior_min)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", prior_size)
            _reset_live_cache()

    return _scope()


# --------------------------------------------------------------- XLA builds
#
# Cold start is made of XLA executable builds, and proving a warm bundle
# works means COUNTING them: jax's monitoring stream emits
# ``/jax/core/compile/backend_compile_duration`` once per executable
# ACQUISITION (fresh build or persistent-cache retrieval — pxla wraps
# ``compile_or_get_cached`` in it) and ``/jax/compilation_cache/cache_hits``
# once per retrieval, so ``fresh = programs - cache_hits`` holds whether or
# not a persistent cache is configured.  The serve server snapshots these
# around bundle load to publish ``compiles_at_load`` / ``warm_cache_hits``.

_COMPILE_EVENT_COUNTS = {"programs": 0, "cache_hits": 0, "build_s": 0.0}
_COMPILE_COUNTERS_INSTALLED = False


def install_compile_event_counters() -> bool:
    """Idempotently register jax monitoring listeners feeding
    :func:`compile_event_counts`.  Returns False (and stays inert) when
    this jax version has no monitoring stream — callers degrade to
    "warmth unproven", never to a crash."""
    global _COMPILE_COUNTERS_INSTALLED
    if _COMPILE_COUNTERS_INSTALLED:
        return True
    try:
        from jax._src import monitoring
    except Exception:
        return False

    def _on_event(event, **kw):
        if event == "/jax/compilation_cache/cache_hits":
            _COMPILE_EVENT_COUNTS["cache_hits"] += 1

    def _on_duration(event, duration, **kw):
        if event == "/jax/core/compile/backend_compile_duration":
            _COMPILE_EVENT_COUNTS["programs"] += 1
            _COMPILE_EVENT_COUNTS["build_s"] += float(duration)

    try:
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _COMPILE_COUNTERS_INSTALLED = True
    return True


def compile_event_counts() -> dict:
    """Point-in-time copy of the build counters: ``programs`` (executable
    acquisitions), ``cache_hits`` (persistent-cache retrievals among
    them), ``build_s`` (wall seconds in acquisition — retrievals included,
    they are milliseconds).  Delta two snapshots around a load to get the
    load's fresh-build count: ``(programs - cache_hits)`` after minus
    before."""
    return dict(_COMPILE_EVENT_COUNTS)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: new jax exposes ``jax.shard_map``
    (replication check kwarg ``check_vma``); 0.4.x only has
    ``jax.experimental.shard_map.shard_map`` with the same check spelled
    ``check_rep``.  Single shim so call sites never branch on version."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def set_host_device_count_flag(n_devices: int) -> None:
    """Set (or REPLACE) ``--xla_force_host_platform_device_count`` in
    ``XLA_FLAGS`` — the version-portable spelling of
    ``jax_num_cpu_devices``.  Only effective before the backend
    initializes.  Replacing an existing value matters: inheriting a
    different count from the environment silently changes the mesh the
    8-device sharding tests assert on."""
    import os
    import re

    n = max(int(n_devices), 1)
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags


def enable_cpu_gloo_collectives() -> bool:
    """Route CPU cross-process collectives through Gloo — required for
    ``jax.distributed`` multi-process runs on the CPU backend (the
    default CPU client answers any cross-process psum with
    "Multiprocess computations aren't implemented").  Version-portable:
    jax 0.4.x spells it ``jax_cpu_collectives_implementation``; where
    only the older boolean exists that is set instead.  Only effective
    before the backend initializes; returns True when a knob took."""
    import jax

    for name, value in (("jax_cpu_collectives_implementation", "gloo"),
                        ("jax_cpu_enable_gloo_collectives", True)):
        try:
            jax.config.update(name, value)
            return True
        except Exception:  # noqa: BLE001 — knob absent in this version
            continue
    return False


def force_cpu_backend(n_devices: int = 8) -> bool:
    """Best-effort switch to the CPU backend with ``n_devices`` virtual
    devices.  Returns True if the config took; False if the backend was
    already initialized (caller proceeds with whatever is live)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return False
    try:
        jax.config.update("jax_num_cpu_devices", max(int(n_devices), 1))
    except AttributeError:
        # older jax has no jax_num_cpu_devices; importing jax does not
        # initialize a backend, so the env flag still takes effect here
        set_host_device_count_flag(n_devices)
    except Exception:
        return False
    return True
