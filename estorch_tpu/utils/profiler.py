"""Backward-compat shim: the profiling hooks moved to
:mod:`estorch_tpu.obs.trace` (the observability subsystem,
docs/observability.md).  Import from ``estorch_tpu.obs`` in new code;
this module keeps the historical ``utils.profiler`` surface alive.
"""

from __future__ import annotations

from ..obs.trace import annotate, timed_generations, trace  # noqa: F401

__all__ = ["trace", "annotate", "timed_generations"]
