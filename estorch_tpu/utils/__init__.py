from .backend import (enable_compilation_cache, force_cpu_backend,
                      set_host_device_count_flag)
from .checkpoint import (PeriodicCheckpointer, latest_checkpoint,
                         restore_checkpoint, save_checkpoint)
from .fault import mask_and_renormalize, rank_weights_with_failures, valid_mask
from .metrics import JsonlWriter, MultiWriter, TensorBoardWriter
from .profiler import annotate, timed_generations, trace

__all__ = [
    "enable_compilation_cache",
    "force_cpu_backend",
    "set_host_device_count_flag",
    "PeriodicCheckpointer",
    "latest_checkpoint",
    "restore_checkpoint",
    "save_checkpoint",
    "mask_and_renormalize",
    "rank_weights_with_failures",
    "valid_mask",
    "JsonlWriter",
    "MultiWriter",
    "TensorBoardWriter",
    "annotate",
    "timed_generations",
    "trace",
]
