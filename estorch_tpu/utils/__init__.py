from .backend import (compile_event_counts, enable_compilation_cache,
                      enable_cpu_gloo_collectives, force_cpu_backend,
                      install_compile_event_counters,
                      scoped_compilation_cache, set_host_device_count_flag)
from .checkpoint import (PeriodicCheckpointer, latest_checkpoint,
                         restore_checkpoint, save_checkpoint)
from .fault import mask_and_renormalize, rank_weights_with_failures, valid_mask
from .metrics import JsonlWriter, MultiWriter, TensorBoardWriter
from .profiler import annotate, timed_generations, trace

__all__ = [
    "compile_event_counts",
    "enable_compilation_cache",
    "enable_cpu_gloo_collectives",
    "force_cpu_backend",
    "install_compile_event_counters",
    "scoped_compilation_cache",
    "set_host_device_count_flag",
    "PeriodicCheckpointer",
    "latest_checkpoint",
    "restore_checkpoint",
    "save_checkpoint",
    "mask_and_renormalize",
    "rank_weights_with_failures",
    "valid_mask",
    "JsonlWriter",
    "MultiWriter",
    "TensorBoardWriter",
    "annotate",
    "timed_generations",
    "trace",
]
