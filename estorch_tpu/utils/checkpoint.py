"""Checkpoint / resume — exact-state persistence via Orbax.

The reference has NOTHING here: users ``torch.save`` the policy state_dict
by hand and lose optimizer moments, RNG position, the novelty archive, and
the NSRA weight (SURVEY.md §5 'Checkpoint / resume').  estorch_tpu
checkpoints the FULL algorithm state, so resume is bit-exact: the noise
stream is derived from ``(key, generation)``, hence restoring those two plus
params/optimizer reproduces the run as if never interrupted.

Layout of a checkpoint directory:
- ``state/``    — Orbax tree of all numeric state (params, optax state, rng
                  key, generation counters, best snapshot, archive BCs,
                  meta-population centers)
- ``meta.json`` — strings/flags (backend, algo, config echo, NSRA scalars)
- ``host_opt.pt`` — host backend only: torch optimizer state_dicts
                  (torch-native serialization, one per center)
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np


def _np(x):
    return np.asarray(x)


def _pack_state(es, st) -> dict:
    """Numeric-only view of one engine state (device ESState or HostState)."""
    d = {
        "params_flat": _np(st.params_flat),
        "generation": int(st.generation),
    }
    # host states may carry the None sentinel (pre-sigma-field, engine falls
    # back to its init σ) — persist that fallback value, not a crash
    d["sigma"] = float(es.engine.sigma if st.sigma is None else st.sigma)
    if es.backend == "host":
        d["key"] = int(st.key)
    else:
        d["key"] = _np(st.key)
        d["opt_state"] = _to_numpy_tree(st.opt_state)
        if getattr(st, "obs_stats", None) is not None:
            d["obs_stats"] = _to_numpy_tree(st.obs_stats)
    return d


def _to_numpy_tree(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(_np, tree)


def _all_states(es) -> list:
    return list(es.meta_states) if hasattr(es, "meta_states") else [es.state]


def _state_tree(es) -> dict:
    """The numeric state tree (Orbax-safe: arrays/ints/floats only)."""
    tree = {
        "generation": int(es.generation),
        "best_reward": float(es.best_reward) if np.isfinite(es.best_reward) else -1e30,
        "has_best": int(es._best_flat is not None),
        "best_flat": (
            _np(es._best_flat)
            if es._best_flat is not None
            else np.zeros(0, np.float32)
        ),
        "states": [_pack_state(es, s) for s in _all_states(es)],
    }
    if hasattr(es, "archive"):
        tree["archive_bcs"] = es.archive.bcs
        tree["center_bc"] = [_np(b) for b in es._center_bc]
    return tree


CHECKPOINT_FORMAT_VERSION = 3  # v3: HOST states carry annealable sigma too
# (v2 added it to device states only)


def _meta_dict(es) -> dict:
    meta = {
        "format_version": CHECKPOINT_FORMAT_VERSION,
        "backend": es.backend,
        "algo": type(es).__name__,
        "population_size": es.population_size,
        "sigma": es.sigma,
        "seed": es.seed,
        "generation": int(es.generation),
        "history_len": len(es.history),
        # state-SCHEMA flag: obs_norm adds obs_stats to every device state;
        # restoring across a mismatch would otherwise fail deep inside
        # Orbax (template mismatch) or silently drop the stats
        "obs_norm": bool(getattr(es, "_obs_norm", False)),
    }
    if hasattr(es, "archive"):
        meta["archive_k"] = es.archive.k
        meta["archive_bc_dim"] = es.archive.bc_dim
        meta["archive_max_size"] = es.archive.max_size
    if hasattr(es, "weight"):  # NSRA
        meta["nsra_weight"] = float(es.weight)
        meta["nsra_stagnation"] = int(es._stagnation)
    if hasattr(es, "_rng"):
        # meta-selection RNG position — without it a resumed novelty run
        # picks different meta-individuals than the uninterrupted run
        meta["meta_rng_state"] = es._rng.bit_generator.state
    return meta


class AsyncSaveHandle:
    """Returned by ``save_checkpoint(..., asynchronous=True)``: the array
    write continues in Orbax's background thread while training proceeds.
    Call :meth:`wait` (idempotent) before restoring from the path or
    exiting the process."""

    def __init__(self, ckptr, owned: bool = True):
        self._ckptr = ckptr
        self._owned = owned  # shared checkpointers (PeriodicCheckpointer)
        # are closed by their owner, not per-save
        self._done = False

    def wait(self) -> None:
        if not self._done:
            self._ckptr.wait_until_finished()
            if self._owned:
                self._ckptr.close()
            self._done = True


def save_checkpoint(es, path: str, asynchronous: bool = False,
                    _async_ckptr=None):
    """Write a complete checkpoint of ``es`` to directory ``path``.

    ``asynchronous=True``: the device→disk array write happens in Orbax's
    background thread, so on a real accelerator the training loop is not
    blocked for the save's disk time (JAX snapshots the on-device values
    at save-call time — later training steps cannot corrupt the write).
    Returns an :class:`AsyncSaveHandle`; call ``.wait()`` before restoring
    or process exit.  Synchronous saves return ``None``.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    # sidecar files FIRST, Orbax payload LAST: the finalized state/ dir is
    # the commit point (Orbax writes to a tmp dir and renames), so a crash
    # at ANY earlier moment leaves a directory that latest_checkpoint()
    # skips — never a restorable-looking checkpoint missing its sidecars
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(_meta_dict(es), f, indent=2)
    # per-generation records survive resume (meta's history_len cross-checks)
    with open(os.path.join(path, "history.json"), "w") as f:
        json.dump(es.history, f)
    if es.backend == "host":
        import torch

        torch.save(
            [s.opt_state for s in _all_states(es)],
            os.path.join(path, "host_opt.pt"),
        )
    # deterministic chaos: a scheduled mid-checkpoint-write crash lands
    # exactly here — sidecars written, payload not finalized
    from ..resilience.chaos import crash_checkpoint

    crash_checkpoint(es.generation)
    if asynchronous:
        # _async_ckptr: a long-lived checkpointer supplied by the caller
        # (PeriodicCheckpointer) — Orbax's intended reuse pattern; a bare
        # call gets its own, closed by the handle's wait()
        ckptr = _async_ckptr or ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler()
        )
        ckptr.save(
            os.path.join(path, "state"),
            args=ocp.args.StandardSave(_state_tree(es)),
            force=True,
        )
        return AsyncSaveHandle(ckptr, owned=_async_ckptr is None)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "state"), _state_tree(es), force=True)
    ckptr.wait_until_finished()
    return None


def restore_checkpoint(es, path: str) -> None:
    """Restore ``es`` in place from a checkpoint written by save_checkpoint.

    ``es`` must be constructed with the same configuration (policy, agent,
    optimizer, population, sigma, seed) — the standard JAX restore pattern:
    rebuild the program, then load the state.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    version = meta.get("format_version", 0)
    # v3 only added sigma to HOST states; a v2 DEVICE/POOLED checkpoint's
    # payload is byte-identical to v3 and remains loadable
    v2_compatible = version == 2 and meta.get("backend") != "host"
    if version != CHECKPOINT_FORMAT_VERSION and not v2_compatible:
        raise ValueError(
            f"checkpoint format v{version} != supported "
            f"v{CHECKPOINT_FORMAT_VERSION} (older states lack the annealable "
            "sigma field — v2 device-only, v3 all backends); re-save from "
            "the run that wrote it"
        )
    if meta["backend"] != es.backend:
        raise ValueError(
            f"checkpoint backend {meta['backend']!r} != this object's {es.backend!r}"
        )
    if meta["algo"] != type(es).__name__:
        raise ValueError(
            f"checkpoint algo {meta['algo']!r} != this object's {type(es).__name__!r}"
        )
    # schema gate: obs_norm changes every device state's shape (obs_stats).
    # Checkpoints from before the flag existed lack the key → treated as
    # written with obs_norm off.
    ck_obs_norm = bool(meta.get("obs_norm", False))
    es_obs_norm = bool(getattr(es, "_obs_norm", False))
    if ck_obs_norm != es_obs_norm:
        raise ValueError(
            f"checkpoint was written with obs_norm={ck_obs_norm} but this "
            f"object was constructed with obs_norm={es_obs_norm} — rebuild "
            "with the matching setting (the running obs stats are part of "
            f"training state), e.g. pass obs_norm={ck_obs_norm} to the "
            "constructor or config recipe (humanoid2d_device/_pop10k "
            "default obs_norm=True since round 4; older checkpoints need "
            "the explicit obs_norm=False override)"
        )

    # An async save writes meta.json immediately while the Orbax array
    # drain runs in the background (Orbax writes to a tmp dir and renames
    # on finalize) — so a path can pass every meta/schema check above and
    # still have no restorable payload.  Catch it here with a clear error
    # instead of a deep Orbax FileNotFoundError.
    state_dir = os.path.join(path, "state")
    if not os.path.isdir(state_dir):
        raise ValueError(
            f"checkpoint at {path!r} has no finalized state/ payload — "
            "an async save is still draining (call handle.wait() / "
            "PeriodicCheckpointer.wait() first) or the write crashed "
            "mid-save; use PeriodicCheckpointer.latest() to find the "
            "newest restorable checkpoint"
        )

    ckptr = ocp.StandardCheckpointer()
    tree = ckptr.restore(state_dir, _state_tree(es))

    es.generation = int(tree["generation"])
    br = float(tree["best_reward"])
    es.best_reward = -np.inf if br <= -1e29 else br
    es._best_flat = _np(tree["best_flat"]) if int(tree["has_best"]) else None

    hist_path = os.path.join(path, "history.json")
    if os.path.exists(hist_path):  # absent in pre-round-2 checkpoints
        with open(hist_path) as f:
            es.history = json.load(f)
        if len(es.history) != meta.get("history_len", len(es.history)):
            import warnings

            warnings.warn(
                f"checkpoint history.json holds {len(es.history)} records "
                f"but meta.json recorded {meta['history_len']} — the "
                "checkpoint write was likely interrupted; records may be "
                "stale/partial (numeric state is unaffected)",
                stacklevel=2,
            )

    host_opts = None
    if es.backend == "host":
        import torch

        host_opts = torch.load(
            os.path.join(path, "host_opt.pt"), weights_only=False
        )

    states = [
        _unpack_state(es, packed, None if host_opts is None else host_opts[i])
        for i, packed in enumerate(tree["states"])
    ]
    if hasattr(es, "meta_states"):
        es.meta_states = states
    es.state = states[0]

    if hasattr(es, "archive"):
        from ..algo.archive import NoveltyArchive

        es.archive = NoveltyArchive.from_state_dict(
            {
                "k": meta["archive_k"],
                "bc_dim": meta["archive_bc_dim"],
                "max_size": meta.get("archive_max_size", 0),
                "bcs": _np(tree["archive_bcs"]),
            }
        )
        es._center_bc = [_np(b) for b in tree["center_bc"]]
    if "nsra_weight" in meta and hasattr(es, "weight"):
        es.weight = float(meta["nsra_weight"])
        es._stagnation = int(meta["nsra_stagnation"])
    if "meta_rng_state" in meta and hasattr(es, "_rng"):
        es._rng = np.random.default_rng()
        es._rng.bit_generator.state = meta["meta_rng_state"]


def _unpack_state(es, packed: dict, host_opt=None):
    if es.backend == "host":
        from ..host.engine import HostState

        return HostState(
            params_flat=_np(packed["params_flat"]).astype(np.float32),
            opt_state=host_opt,
            key=int(packed["key"]),
            generation=int(packed["generation"]),
            sigma=float(packed["sigma"]),
        )
    import jax.numpy as jnp

    from ..parallel.engine import ESState

    obs_stats = packed.get("obs_stats")
    if obs_stats is not None:
        obs_stats = tuple(
            jnp.asarray(x, jnp.float32) for x in obs_stats
        )
    return ESState(
        params_flat=jnp.asarray(packed["params_flat"]),
        opt_state=packed["opt_state"],
        key=jnp.asarray(packed["key"]),
        generation=jnp.int32(packed["generation"]),
        sigma=jnp.float32(packed["sigma"]),
        obs_stats=obs_stats,
    )


def latest_checkpoint(root: str) -> str | None:
    """Newest checkpoint under ``root`` whose Orbax payload is FINALIZED.

    An async save mid-drain, or a crash mid-write, leaves meta.json
    without a ``state/`` dir (Orbax writes to a tmp dir and renames on
    finalize) — such a directory must not shadow the older restorable
    one.  Module-level so supervisors (resilience/supervisor.py) can find
    the resume point without constructing an ES first."""
    try:
        cks = sorted(d for d in os.listdir(root) if d.startswith("gen_"))
    except OSError:
        return None
    for d in reversed(cks):
        if os.path.isdir(os.path.join(root, d, "state")):
            return os.path.join(root, d)
    return None


class PeriodicCheckpointer:
    """Save every K generations; keeps the newest ``max_to_keep`` checkpoints.

    Usage (composes with train's log_fn):
        ck = PeriodicCheckpointer(es, "ckpts", every=10)
        es.train(100, log_fn=ck.on_record)
    """

    def __init__(self, es, root: str, every: int = 10, max_to_keep: int = 3,
                 asynchronous: bool = False):
        self.es = es
        self.root = os.path.abspath(root)
        self.every = int(every)
        self.max_to_keep = int(max_to_keep)
        # asynchronous: each save's array write drains in Orbax's
        # background thread while training continues; the previous save is
        # awaited before the next one starts (at most one write in flight),
        # and ONE long-lived AsyncCheckpointer serves every save
        self.asynchronous = bool(asynchronous)
        self._pending = None
        self._ckptr = None
        if self.asynchronous:
            import orbax.checkpoint as ocp

            self._ckptr = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler()
            )
        os.makedirs(self.root, exist_ok=True)

    def on_record(self, record: dict) -> None:
        gen = record["generation"]
        if (gen + 1) % self.every == 0:
            self.save(gen)

    def save(self, gen: int) -> str:
        self.wait()
        path = os.path.join(self.root, f"gen_{gen:08d}")
        self._pending = save_checkpoint(
            self.es, path, asynchronous=self.asynchronous,
            _async_ckptr=self._ckptr,
        )
        if self._pending is None:
            self._gc()  # sync save: already durable
        # async: GC is DEFERRED to wait() — collecting now could delete the
        # last durable checkpoint while this one is still draining, leaving
        # nothing restorable if the process dies mid-write
        return path

    def wait(self) -> None:
        """Block until the in-flight async save (if any) is durable, then
        collect stale checkpoints.  Called automatically before each new
        save; call it yourself before reading ``latest()`` or exiting."""
        if self._pending is not None:
            self._pending.wait()
            self._pending = None
            self._gc()

    def close(self) -> None:
        """Drain the in-flight save and release the async checkpointer."""
        self.wait()
        if self._ckptr is not None:
            self._ckptr.close()
            self._ckptr = None

    def latest(self) -> str | None:
        """Newest restorable checkpoint (see :func:`latest_checkpoint`)."""
        return latest_checkpoint(self.root)

    def _gc(self) -> None:
        import shutil

        cks = sorted(d for d in os.listdir(self.root) if d.startswith("gen_"))
        for stale in cks[: -self.max_to_keep]:
            shutil.rmtree(os.path.join(self.root, stale), ignore_errors=True)
