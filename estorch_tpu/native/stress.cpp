// stress.cpp — sanitizer stress driver for the envpool thread team.
//
// SURVEY.md §5 'Race detection': the reference has no native code to
// sanitize; estorch_tpu's one native component is this pool, so its worker
// synchronization (epoch broadcast + completion counter, envpool.cpp) gets
// a TSan/ASan job.  Build and run:
//
//   make -C estorch_tpu/native tsan && ./estorch_tpu/native/stress_tsan
//   make -C estorch_tpu/native asan && ./estorch_tpu/native/stress_asan
//
// Exercises: many generations of reset/step across all three envs with
// maximum thread counts, pool churn (create/destroy), and odd env/thread
// ratios.  Exits 0 when clean; sanitizers abort on any race/leak.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <vector>

extern "C" {
void* envpool_create(int env_id, int n_envs, int n_threads, uint64_t seed);
void envpool_destroy(void* h);
int envpool_obs_dim(void* h);
int envpool_act_dim(void* h);
void envpool_reset(void* h, float* obs_out);
void envpool_step(void* h, const float* actions, float* obs_out,
                  float* rew_out, uint8_t* done_out);
}

static void hammer(int env_id, int n_envs, int n_threads, int steps) {
  void* h = envpool_create(env_id, n_envs, n_threads, 42);
  if (!h) {
    std::fprintf(stderr, "create failed (%d, %d, %d)\n", env_id, n_envs, n_threads);
    std::exit(1);
  }
  const int od = envpool_obs_dim(h);
  const int ad = envpool_act_dim(h);
  std::vector<float> obs(static_cast<size_t>(n_envs) * od);
  std::vector<float> act(static_cast<size_t>(n_envs) * ad, 1.0f);
  std::vector<float> rew(n_envs);
  std::vector<uint8_t> done(n_envs);
  envpool_reset(h, obs.data());
  for (int t = 0; t < steps; t++) {
    envpool_step(h, act.data(), obs.data(), rew.data(), done.data());
  }
  envpool_destroy(h);
}

int main() {
  // thread/env ratios incl. n_threads > n_envs and prime counts
  for (int env_id = 0; env_id <= 2; env_id++) {
    const int steps = env_id == 2 ? 50 : 400;  // pixels are heavier
    hammer(env_id, 64, 1, steps);
    hammer(env_id, 64, 7, steps);
    hammer(env_id, 64, 16, steps);
    hammer(env_id, 3, 16, steps);   // more threads than envs
    hammer(env_id, 1, 1, steps);
  }
  // rapid create/destroy churn (worker startup/shutdown races)
  for (int i = 0; i < 20; i++) hammer(0, 8, 4, 5);
  std::puts("stress: OK");
  return 0;
}
