// envpool.cpp — batched environment stepping with a persistent thread pool.
//
// The native runtime component of estorch_tpu (SURVEY.md §2: the reference
// is pure Python and eats the env-stepping cost in per-process Python loops;
// the rebuild's host pipeline replaces that with a C++ pthread env-stepper,
// envpool-style).  This pool steps N classic-control envs in parallel worker
// threads behind a C API consumed via ctypes (envs/native_pool.py), feeding
// device-batched policy inference without per-step Python overhead.
//
// Envs implemented: CartPole-v1 (id 0) and Pendulum-v1 (id 1), matching the
// gymnasium dynamics exactly like the pure-JAX twins (envs/cartpole.py,
// envs/pendulum.py) — the three implementations are parity-tested against
// each other in tests/test_native_pool.py.
//
// Build: make -C estorch_tpu/native   (g++ -O3 -shared -fPIC, pthreads)

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

constexpr float kPi = 3.14159265358979323846f;

// ----------------------------------------------------------------- envs

struct CartPoleEnv {
  static constexpr int kObsDim = 4;
  static constexpr int kActDim = 1;  // discrete {0,1} passed as float
  static constexpr float kGravity = 9.8f, kMassCart = 1.0f, kMassPole = 0.1f;
  static constexpr float kLength = 0.5f, kForceMag = 10.0f, kTau = 0.02f;
  static constexpr float kThetaThreshold = 12.0f * 2.0f * kPi / 360.0f;
  static constexpr float kXThreshold = 2.4f;

  float s[4];

  void reset(std::mt19937& rng) {
    std::uniform_real_distribution<float> d(-0.05f, 0.05f);
    for (int i = 0; i < 4; i++) s[i] = d(rng);
  }

  // returns done; reward is always 1.0 for an alive step
  bool step(const float* action, float* reward) {
    const float force = (action[0] > 0.5f) ? kForceMag : -kForceMag;
    const float x = s[0], x_dot = s[1], theta = s[2], theta_dot = s[3];
    const float costh = std::cos(theta), sinth = std::sin(theta);
    const float total_mass = kMassCart + kMassPole;
    const float pml = kMassPole * kLength;
    const float temp = (force + pml * theta_dot * theta_dot * sinth) / total_mass;
    const float thetaacc =
        (kGravity * sinth - costh * temp) /
        (kLength * (4.0f / 3.0f - kMassPole * costh * costh / total_mass));
    const float xacc = temp - pml * thetaacc * costh / total_mass;
    s[0] = x + kTau * x_dot;
    s[1] = x_dot + kTau * xacc;
    s[2] = theta + kTau * theta_dot;
    s[3] = theta_dot + kTau * thetaacc;
    *reward = 1.0f;
    return std::fabs(s[0]) > kXThreshold || std::fabs(s[2]) > kThetaThreshold;
  }

  void observe(float* obs) const { std::memcpy(obs, s, sizeof(s)); }
};

struct PendulumEnv {
  static constexpr int kObsDim = 3;
  static constexpr int kActDim = 1;
  static constexpr float kMaxSpeed = 8.0f, kMaxTorque = 2.0f, kDt = 0.05f;
  static constexpr float kG = 10.0f, kM = 1.0f, kL = 1.0f;

  float th, thdot;

  void reset(std::mt19937& rng) {
    std::uniform_real_distribution<float> dth(-kPi, kPi);
    std::uniform_real_distribution<float> dv(-1.0f, 1.0f);
    th = dth(rng);
    thdot = dv(rng);
  }

  static float angle_normalize(float x) {
    return std::fmod(x + kPi, 2.0f * kPi) < 0
               ? std::fmod(x + kPi, 2.0f * kPi) + 2.0f * kPi - kPi
               : std::fmod(x + kPi, 2.0f * kPi) - kPi;
  }

  bool step(const float* action, float* reward) {
    float u = action[0];
    u = u < -kMaxTorque ? -kMaxTorque : (u > kMaxTorque ? kMaxTorque : u);
    const float an = angle_normalize(th);
    const float cost = an * an + 0.1f * thdot * thdot + 0.001f * u * u;
    float newthdot =
        thdot + (3.0f * kG / (2.0f * kL) * std::sin(th) +
                 3.0f / (kM * kL * kL) * u) * kDt;
    newthdot = newthdot < -kMaxSpeed ? -kMaxSpeed
                                     : (newthdot > kMaxSpeed ? kMaxSpeed : newthdot);
    th = th + newthdot * kDt;
    thdot = newthdot;
    *reward = -cost;
    return false;  // pendulum never terminates
  }

  void observe(float* obs) const {
    obs[0] = std::cos(th);
    obs[1] = std::sin(th);
    obs[2] = thdot;
  }
};

// Pong84 (env id 2): a minimal pixel pong rendered to 84x84x1 — the
// conv-rollout stress stand-in for the Atari config (BASELINE config 5) in
// an image without ALE.  The agent drives the LEFT paddle with 3 actions
// (stay/up/down); the right paddle is a simple ball tracker.  Reward +1
// when the opponent misses, -1 when the agent misses; after each point the
// ball re-serves and play continues — the episode ends when either side
// reaches kWinScore points (ALE Pong's play-to-21 match structure), so
// returns span multiple rallies like the real game.  Observation:
// normalized float32 pixels in [0, 1] (ball and paddles drawn white on
// black), flattened row-major 84*84.
struct Pong84Env {
  static constexpr int kSize = 84;
  static constexpr int kObsDim = kSize * kSize;
  static constexpr int kActDim = 1;  // discrete {0,1,2} passed as float
  static constexpr float kPaddleSpeed = 2.0f;
  static constexpr float kOppSpeed = 1.2f;   // beatable tracker
  static constexpr int kPaddleHalf = 6;      // paddle half-height in px
  static constexpr float kBallSpeed = 1.6f;

  static constexpr int kWinScore = 21;  // ALE Pong match length

  float ball_x, ball_y, vel_x, vel_y;  // pixel coordinates
  float left_y, right_y;               // paddle centers
  int left_score, right_score;

  void serve(std::mt19937& rng) {
    std::uniform_real_distribution<float> dy(20.0f, 64.0f);
    std::uniform_real_distribution<float> dv(-0.8f, 0.8f);
    ball_x = kSize / 2.0f;
    ball_y = dy(rng);
    vel_x = (rng() & 1) ? kBallSpeed : -kBallSpeed;
    vel_y = dv(rng);
  }

  void reset(std::mt19937& rng) {
    serve(rng);
    left_y = kSize / 2.0f;
    right_y = kSize / 2.0f;
    left_score = 0;
    right_score = 0;
  }

  bool step(const float* action, float* reward, std::mt19937& rng) {
    const int a = static_cast<int>(action[0] + 0.5f);
    if (a == 1) left_y -= kPaddleSpeed;
    else if (a == 2) left_y += kPaddleSpeed;
    left_y = left_y < kPaddleHalf ? kPaddleHalf
             : (left_y > kSize - kPaddleHalf ? kSize - kPaddleHalf : left_y);

    // opponent tracks the ball with capped speed
    const float dy = ball_y - right_y;
    right_y += dy > kOppSpeed ? kOppSpeed : (dy < -kOppSpeed ? -kOppSpeed : dy);
    right_y = right_y < kPaddleHalf ? kPaddleHalf
              : (right_y > kSize - kPaddleHalf ? kSize - kPaddleHalf : right_y);

    ball_x += vel_x;
    ball_y += vel_y;
    if (ball_y < 1.0f) { ball_y = 1.0f; vel_y = -vel_y; }
    if (ball_y > kSize - 1.0f) { ball_y = kSize - 1.0f; vel_y = -vel_y; }

    *reward = 0.0f;
    // left paddle plane at x=3, right at x=80
    if (ball_x <= 3.0f) {
      if (std::fabs(ball_y - left_y) <= kPaddleHalf + 1.0f) {
        vel_x = -vel_x;
        ball_x = 3.0f;
        std::uniform_real_distribution<float> spin(-0.5f, 0.5f);
        vel_y += spin(rng);
      } else {
        *reward = -1.0f;
        right_score++;
        if (right_score >= kWinScore) return true;
        serve(rng);  // point over, next rally
        return false;
      }
    }
    if (ball_x >= kSize - 4.0f) {
      if (std::fabs(ball_y - right_y) <= kPaddleHalf + 1.0f) {
        vel_x = -vel_x;
        ball_x = kSize - 4.0f;
      } else {
        *reward = 1.0f;
        left_score++;
        if (left_score >= kWinScore) return true;
        serve(rng);
        return false;
      }
    }
    return false;
  }

  void observe(float* obs) const {
    std::memset(obs, 0, sizeof(float) * kObsDim);
    auto draw = [obs](int x, int y) {
      if (x >= 0 && x < kSize && y >= 0 && y < kSize) obs[y * kSize + x] = 1.0f;
    };
    const int by = static_cast<int>(ball_y);
    const int bx = static_cast<int>(ball_x);
    for (int dy = -1; dy <= 1; dy++)
      for (int dx = -1; dx <= 1; dx++) draw(bx + dx, by + dy);
    for (int dy = -kPaddleHalf; dy <= kPaddleHalf; dy++) {
      draw(2, static_cast<int>(left_y) + dy);
      draw(3, static_cast<int>(left_y) + dy);
      draw(kSize - 4, static_cast<int>(right_y) + dy);
      draw(kSize - 3, static_cast<int>(right_y) + dy);
    }
  }
};

// ------------------------------------------------------------ thread pool

// One pool = N envs of one type + a persistent worker team.  Workers park on
// a condition variable between generations; step() broadcasts a job (epoch
// bump), workers each process a contiguous env slice, and the caller waits
// on a completion counter.  No per-step thread spawn, no Python in the loop.
class Pool {
 public:
  Pool(int env_id, int n_envs, int n_threads, uint64_t seed)
      : env_id_(env_id), n_envs_(n_envs),
        n_threads_(n_threads < 1 ? 1 : (n_threads > n_envs ? n_envs : n_threads)) {
    if (env_id_ == 0) cartpoles_.resize(n_envs_);
    else if (env_id_ == 1) pendulums_.resize(n_envs_);
    else pongs_.resize(n_envs_);
    rngs_.reserve(n_envs_);
    for (int i = 0; i < n_envs_; i++) {
      rngs_.emplace_back(static_cast<uint32_t>(seed + 0x9E3779B9u * (i + 1)));
    }
    for (int t = 0; t < n_threads_; t++) {
      workers_.emplace_back([this, t] { worker_loop(t); });
    }
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      shutdown_ = true;
      epoch_++;
    }
    cv_go_.notify_all();
    for (auto& w : workers_) w.join();
  }

  int obs_dim() const {
    if (env_id_ == 0) return CartPoleEnv::kObsDim;
    if (env_id_ == 1) return PendulumEnv::kObsDim;
    return Pong84Env::kObsDim;
  }
  int act_dim() const {
    if (env_id_ == 0) return CartPoleEnv::kActDim;
    if (env_id_ == 1) return PendulumEnv::kActDim;
    return Pong84Env::kActDim;
  }

  void reset(float* obs_out) {
    run_job(Job{JobKind::kReset, nullptr, obs_out, nullptr, nullptr});
  }

  void step(const float* actions, float* obs_out, float* rew_out, uint8_t* done_out) {
    run_job(Job{JobKind::kStep, actions, obs_out, rew_out, done_out});
  }

 private:
  enum class JobKind { kReset, kStep };
  struct Job {
    JobKind kind;
    const float* actions;
    float* obs;
    float* rew;
    uint8_t* done;
  };

  void run_job(Job job) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      job_ = job;
      remaining_.store(n_threads_, std::memory_order_relaxed);
      epoch_++;
    }
    cv_go_.notify_all();
    std::unique_lock<std::mutex> lk(mu_);
    cv_done_.wait(lk, [this] { return remaining_.load(std::memory_order_acquire) == 0; });
  }

  void worker_loop(int t) {
    uint64_t seen_epoch = 0;
    const int chunk = (n_envs_ + n_threads_ - 1) / n_threads_;
    const int begin = t * chunk;
    const int end = begin + chunk > n_envs_ ? n_envs_ : begin + chunk;
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_go_.wait(lk, [&] { return epoch_ != seen_epoch; });
        seen_epoch = epoch_;
        if (shutdown_) return;
        job = job_;
      }
      process(job, begin, end);
      if (remaining_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        std::lock_guard<std::mutex> lk(mu_);
        cv_done_.notify_all();
      }
    }
  }

  void process(const Job& job, int begin, int end) {
    const int od = obs_dim(), ad = act_dim();
    for (int i = begin; i < end; i++) {
      if (job.kind == JobKind::kReset) {
        if (env_id_ == 0) { cartpoles_[i].reset(rngs_[i]); cartpoles_[i].observe(job.obs + i * od); }
        else if (env_id_ == 1) { pendulums_[i].reset(rngs_[i]); pendulums_[i].observe(job.obs + i * od); }
        else { pongs_[i].reset(rngs_[i]); pongs_[i].observe(job.obs + i * od); }
      } else {
        float r = 0.0f;
        bool d;
        if (env_id_ == 0) {
          d = cartpoles_[i].step(job.actions + i * ad, &r);
          // auto-reset so downstream batching never sees a dead env
          if (d) cartpoles_[i].reset(rngs_[i]);
          cartpoles_[i].observe(job.obs + i * od);
        } else if (env_id_ == 1) {
          d = pendulums_[i].step(job.actions + i * ad, &r);
          if (d) pendulums_[i].reset(rngs_[i]);
          pendulums_[i].observe(job.obs + i * od);
        } else {
          d = pongs_[i].step(job.actions + i * ad, &r, rngs_[i]);
          if (d) pongs_[i].reset(rngs_[i]);
          pongs_[i].observe(job.obs + i * od);
        }
        job.rew[i] = r;
        job.done[i] = d ? 1 : 0;
      }
    }
  }

  const int env_id_, n_envs_, n_threads_;
  std::vector<CartPoleEnv> cartpoles_;
  std::vector<PendulumEnv> pendulums_;
  std::vector<Pong84Env> pongs_;
  std::vector<std::mt19937> rngs_;
  std::vector<std::thread> workers_;

  std::mutex mu_;
  std::condition_variable cv_go_, cv_done_;
  Job job_{};
  uint64_t epoch_ = 0;
  std::atomic<int> remaining_{0};
  bool shutdown_ = false;
};

}  // namespace

extern "C" {

void* envpool_create(int env_id, int n_envs, int n_threads, uint64_t seed) {
  if (env_id < 0 || env_id > 2 || n_envs <= 0) return nullptr;
  return new Pool(env_id, n_envs, n_threads, seed);
}

void envpool_destroy(void* h) { delete static_cast<Pool*>(h); }

int envpool_obs_dim(void* h) { return static_cast<Pool*>(h)->obs_dim(); }
int envpool_act_dim(void* h) { return static_cast<Pool*>(h)->act_dim(); }

void envpool_reset(void* h, float* obs_out) {
  static_cast<Pool*>(h)->reset(obs_out);
}

void envpool_step(void* h, const float* actions, float* obs_out,
                  float* rew_out, uint8_t* done_out) {
  static_cast<Pool*>(h)->step(actions, obs_out, rew_out, done_out);
}

}  // extern "C"
