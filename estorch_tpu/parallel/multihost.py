"""Multi-host / multi-slice launch helpers.

The reference scales with ``mpirun``-style process groups and
``torch.distributed`` CPU collectives (SURVEY.md §2 item 7).  The TPU-native
equivalent is JAX's multi-process runtime: one Python process per host, each
seeing its local chips, with XLA collectives spanning all of them — ICI
inside a slice, DCN across slices — through the SAME ``lax.psum`` the
single-host engine already emits.  Nothing in the generation program changes
with scale; only the mesh does.

Launch recipe (one command per host):

    # host 0 .. N-1, e.g. under SLURM/GKE each process runs:
    import estorch_tpu.parallel.multihost as mh
    mh.initialize()                    # env-driven (TPU pods auto-discover)
    es = ES(..., mesh=mh.global_population_mesh())
    es.train(...)                      # identical code to single host

Design notes for the broadcast-free update in multi-process SPMD:

- every process constructs the identical ESState (same seed), and every
  jitted program input is fully replicated (P()), so processes stay
  bit-synchronized without any parameter broadcast — the same property the
  single-host engine has across devices;
- the population axis spans ALL global devices; each host's chips roll out
  their shard and the psum's DCN leg only carries O(dim) floats per
  generation plus the O(population) fitness all_gather;
- host-side novelty state (archive, meta-selection RNG) is derived from
  device-gathered, fully-replicated arrays plus the checkpointed RNG — all
  hosts compute identical archives without communication.

Validation status: exercised with TWO REAL OS PROCESSES (4 CPU devices
each, jax.distributed over Gloo/TCP — the DCN-analog layering) in
tests/test_multiprocess.py: end-to-end ES training with cross-process
collectives, final parameters bit-identical across processes, and matching
the single-process 8-device run to float32 reduction tolerance (~2e-8
relative — the cross-process allreduce may order the sum differently than
the in-process psum).  Real TPU pod hardware remains unvalidated (none
reachable from this environment).
"""

from __future__ import annotations

import jax

from .mesh import hyperscale_mesh, population_mesh


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    telemetry=None,
    timeout_s: float = 300.0,
    cpu_collectives: bool = False,
) -> bool:
    """Bring up the JAX multi-process runtime.  Returns True if distributed
    init actually happened, False for a single-process fallback.

    On Cloud TPU pods / managed clusters ``jax.distributed.initialize()``
    auto-discovers everything from the environment — so we ALWAYS attempt
    it.  Off-cluster, the argless attempt raises; when no arguments were
    given we treat that as a single-process run (the degenerate case the
    rest of the library handles identically).  Explicit arguments are never
    swallowed: failures with them re-raise.  Must be called before any
    device use (no jax API that touches backends runs before the attempt).

    ``timeout_s`` bounds the cluster barrier — a peer that never dials in
    becomes a timed error naming the wedge instead of an unbounded hang
    (esguard R17 unfenced-cross-host-barrier is this rule, mechanized).
    ``cpu_collectives=True`` routes CPU cross-process collectives through
    Gloo (utils/backend.py) — required for the simulated-host runs
    (tests/test_multiprocess.py, ``bench.py --elastic-ab``); harmless and
    ignored on TPU.
    """
    explicit = any(a is not None for a in (coordinator_address, num_processes, process_id))
    import time as _time

    if telemetry is None:
        from ..obs.spans import NULL_TELEMETRY as telemetry  # noqa: N811
    if cpu_collectives:
        from ..utils.backend import enable_cpu_gloo_collectives

        enable_cpu_gloo_collectives()
    t0 = _time.perf_counter()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=max(int(timeout_s), 1),
        )
        # cluster bring-up is the multi-host wedge point (a peer that
        # never dials in hangs everyone here) — record how long it took
        # and who we are, so a pod post-mortem can see which hosts made
        # it through and when
        telemetry.event("distributed_init",
                        dur_s=_time.perf_counter() - t0, **process_info())
        return True
    except Exception as e:
        if explicit:
            telemetry.event("distributed_init_failed",
                            dur_s=_time.perf_counter() - t0, error=repr(e))
            raise
        # not a cluster → single-process run; but say WHY, so an operator on
        # a real pod can tell "not a cluster" from "cluster init failed"
        # (silent fallback would mean N duplicate single-host runs)
        import warnings

        warnings.warn(
            f"jax.distributed.initialize() (argless) failed: {e!r} — "
            "continuing as a single-process run. On a pod, this means the "
            "cluster env was NOT picked up; each host would train "
            "independently.",
            stacklevel=2,
        )
        telemetry.event("distributed_init_fallback",
                        dur_s=_time.perf_counter() - t0, error=repr(e))
        return False


def global_population_mesh():
    """1-D population mesh over ALL devices of ALL processes.

    ``jax.devices()`` in a multi-process runtime returns the global device
    list; the mesh (and hence the psum) spans every chip in the job.
    """
    return population_mesh(jax.devices())


def global_hyperscale_mesh(pop_shards: int | None = None,
                           model_shards: int | None = None):
    """2-D (pop, model) mesh over ALL devices of ALL processes — the
    param-sharded engine (parallel/sharded.py) at pod scale.

    Same global-view contract as the 1-D mesh: every process runs the
    identical jitted program against the global mesh, GSPMD routes the
    model-axis collectives over ICI within a slice and DCN across.  On a
    pod, keep ``model`` within a slice (model_shards ≤ chips per slice)
    so the per-layer collectives never cross DCN; the ``pop`` axis
    tolerates the slower links (its only traffic is the psum'd update
    and the fitness gather).
    """
    return hyperscale_mesh(pop_shards, model_shards, devices=jax.devices())


def process_info() -> dict:
    """Who am I in the job — for logging/checkpoint-leader election."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "is_leader": jax.process_index() == 0,
    }


def train_sync(es, n_steps: int, log_fn=None, verbose: bool = False):
    """The SYNCHRONOUS multihost loop — fully-SPMD ``es.train`` with the
    host-granular chaos hook fired at each generation head.

    This is the barrier the elastic layer (parallel/elastic.py) exists to
    remove: every process steps the same fused program, the psum is a
    fleet-wide barrier, and a ``straggle_host`` event stalling THIS
    process stalls every generation fleet-wide.  ``bench.py
    --elastic-ab`` runs this loop as the baseline leg under the same
    declared plan the elastic leg sees; both fire
    ``resilience.chaos.host_fault(generation_or_dispatch, host_index)``
    so the declared slow host is identically slow in both.
    """
    from ..resilience.chaos import host_fault

    host = jax.process_index()
    for _ in range(int(n_steps)):
        # a kill_host in the SYNC leg means this SPMD process dies — the
        # whole job is gone (no membership to shrink); SIGKILL self so
        # the A/B driver sees exactly what a pod would
        if host_fault(int(es.generation), host):
            import os
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        es.train(1, log_fn=log_fn, verbose=verbose)
    return es


def leader_only(fn):
    """Decorator: run ``fn`` only on process 0 (checkpoint writes, logging).

    All processes compute identical state, so side effects need exactly one
    writer; everyone else gets ``None``.
    """

    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if jax.process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped
