"""PooledEngine — C++ host envs + device-batched policy inference.

The execution model for envs that cannot run on-device (the reference's
Gym/MuJoCo/Atari configs, SURVEY.md §7 'Path B'): N = population envs step
in parallel C++ threads (envs/native_pool.py → native/envpool.cpp) while the
accelerator runs ONE batched forward for the whole population per env step —
(population, obs_dim) in, (population, act_dim) out.  Per-member perturbed
parameters are materialized once per generation from the shared noise table;
the update is the identical psum program as the device path (ESEngine in
update-only mode), so offsets/weights stay bit-consistent between
evaluation and update.

vs the reference's design for the same configs: estorch steps ONE env per
Python process and runs the policy forward per single observation
(SURVEY.md §3.3) — here the policy forward is a population-wide batched
matmul on the MXU and env stepping is native threads, with one
host↔device round-trip per env step instead of per member-step.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..envs.gym_vec_pool import make_pool
from ..obs.spans import NULL_TELEMETRY
from ..ops.noise import member_offsets, pair_signs
from ..utils.fault import rank_weights_with_failures
from .engine import ESEngine, ESState


class PooledEvalResult:
    def __init__(self, fitness, bc, steps):
        self.fitness = fitness
        self.bc = bc
        self.steps = steps


class PooledEngine:
    """Same engine interface as ESEngine/HostEngine, pooled evaluation."""

    # span telemetry hub; ES replaces this with its own (obs/spans.py)
    telemetry = NULL_TELEMETRY

    def __init__(
        self,
        env_name: str,
        policy_apply,
        spec,
        table,
        optimizer,
        config,
        mesh,
        n_threads: int = 0,
        seed: int = 0,
        double_buffer: bool = False,
        prep: dict | None = None,
        carry_init=None,
        env_kwargs: dict | None = None,
        bc_indices=None,
    ):
        self.env_name = env_name
        self.env_kwargs = dict(env_kwargs) if env_kwargs else None
        self.prep = dict(prep) if prep else None
        self.spec = spec
        self.config = config
        if config.episodes_per_member != 1:
            raise ValueError(
                "episodes_per_member is a device-path option; the pooled "
                "path rolls one episode per member env"
            )
        if config.streamed:
            raise ValueError(
                "streamed is a device-path option; the pooled path's policy "
                "forward runs per env step against materialized thetas"
            )
        if config.decomposed:
            raise ValueError(
                "decomposed is a device-path option; the pooled path "
                "materializes per-member thetas for its batched forward"
            )
        if config.low_rank:
            raise ValueError(
                "low_rank is a device-path option (ops/lowrank.py); the "
                "pooled path materializes per-member thetas"
            )
        # obs_norm on the pooled path: normalization + raw-moment
        # accumulation happen HOST-side in the step loop below (the obs
        # batches are already on the host); the running Welford stats ride
        # ESState.obs_stats exactly like the device path — checkpointed,
        # split==fused — while the CORE update programs stay stats-agnostic
        # (they carry obs_stats through untouched), so the core config has
        # the flag stripped.  Richer than the device path's center-probe:
        # the stats see every member's observations.
        self.obs_norm = bool(config.obs_norm)
        self._obs_clip = float(config.obs_clip)
        self._pending_moments = None
        self._pending_moments_key = None
        if self.obs_norm and self.prep:
            raise ValueError(
                "obs_norm + Atari preprocessing is unsupported: pixel "
                "policies normalize via VBN / their own /255 scaling"
            )
        import dataclasses as _dc

        core_config = (
            _dc.replace(config, obs_norm=False) if self.obs_norm else config
        )
        # update-only device engine: shares offsets/psum/optax with the
        # fully-on-device path; its ctor also applies the compute_dtype wrap
        # (incl. the stateful bf16 shim + carry cast for recurrent policies),
        # which we reuse below instead of wrapping a second time
        self.core = ESEngine(None, policy_apply, spec, table, optimizer,
                             core_config, mesh, carry_init=carry_init)
        policy_apply = self.core.policy_apply
        carry_init = self.core._carry_init  # bf16 path: pre-cast variant
        self.recurrent = carry_init is not None
        self._carry_init = carry_init
        self.double_buffer = bool(double_buffer)
        def _pool(n_envs, threads, pool_seed):
            pool = make_pool(env_name, n_envs, n_threads=threads,
                             seed=pool_seed, env_kwargs=self.env_kwargs)
            if self.prep:
                from ..envs.atari_wrappers import AtariPreprocessPool

                pool = AtariPreprocessPool(pool, seed=pool_seed, **self.prep)
            return pool

        self._make_pool = _pool

        if self.double_buffer:
            half = config.population_size // 2
            if half * 2 != config.population_size or half == 0:
                raise ValueError(
                    "double_buffer needs an even population of at least 2"
                )
            self.pool_a = _pool(half, n_threads, seed)
            self.pool_b = _pool(half, n_threads, seed + 10_007)
            self.pool = self.pool_a  # dims/metadata accessor
        else:
            self.pool = _pool(config.population_size, n_threads, seed)
        # n_threads=0 (auto): a 1-env pool gains nothing from threads, and a
        # nonzero value would trip GymVecPool's unused-n_threads warning
        self.center_pool = _pool(1, 0, seed + 1)
        # BC = final observation, optionally sliced to bc_indices (e.g.
        # (0,) = final x-position when the env exposes it — the canonical
        # locomotion BC the novelty family's archive searches over)
        self._bc_idx = (
            np.asarray(bc_indices, np.intp) if bc_indices is not None else None
        )
        if self._bc_idx is not None:
            if len(self.pool.obs_shape) != 1:
                # the BC frame is the FLAT final obs; on pixel/prep pools
                # the last axis is channels, not the flat vector — slicing
                # there would silently break the archive's (n, bc_dim)
                # contract
                raise ValueError(
                    "bc_indices need a 1-D observation; got obs_shape "
                    f"{self.pool.obs_shape} — pixel policies characterize "
                    "behavior via the full final frame"
                )
            if self._bc_idx.min() < 0 or self._bc_idx.max() >= self.pool.obs_dim:
                raise ValueError(
                    f"bc_indices {list(self._bc_idx)} out of range for "
                    f"obs_dim {self.pool.obs_dim}"
                )
        self.bc_dim = (
            len(self._bc_idx) if self._bc_idx is not None else self.pool.obs_dim
        )
        discrete = self.pool.discrete
        obs_shape = self.pool.obs_shape  # policy-facing shape (pixels etc.)

        # core.policy_apply is the obs/output shim only (engine.py): the
        # bf16 param cast is the caller's job.  Perturbation stays f32; the
        # materialized theta matrix casts ONCE per generation — unravel
        # preserves dtype for single-dtype trees, so every per-step
        # inference below reads bf16 weights with no further casts.
        bf16 = config.compute_dtype == "bfloat16"

        def materialize(params_flat, sigma, all_offs):
            """(population, dim) perturbed parameter matrix from the table.
            ``all_offs`` is per-pair (mirrored) or per-member (unmirrored),
            matching core.all_pair_offsets."""
            if config.mirrored:
                offs = member_offsets(all_offs)
                signs = pair_signs(config.population_size)
            else:
                offs = all_offs
                signs = jnp.ones((config.population_size,), jnp.float32)
            def one(off, sign):
                eps = self.core.table.slice(off, spec.dim)
                return params_flat + sigma * sign * eps
            thetas = jax.vmap(one)(offs, signs)
            return thetas.astype(jnp.bfloat16) if bf16 else thetas

        self._materialize = jax.jit(materialize)

        def _params(flat):
            return spec.unravel(flat.astype(jnp.bfloat16) if bf16 else flat)

        def _act(out):
            """Shared action rule: argmax logits (discrete) / flat values."""
            if discrete:
                return jnp.argmax(out, axis=-1).astype(jnp.float32)
            return out.reshape(-1)

        if self.recurrent:
            # the hidden carry lives host-side across the generation's step
            # loop: (population, …) stacked carries in, stacked carries out
            def batch_actions(thetas, obs, carries):
                def one(theta, o, h):
                    out, h2 = policy_apply(
                        spec.unravel(theta), o.reshape(obs_shape), h
                    )
                    return _act(out), h2
                return jax.vmap(one)(thetas, obs, carries)

            def center_action(params_flat, obs, h):
                out, h2 = policy_apply(
                    _params(params_flat), obs.reshape(obs_shape), h
                )
                return _act(out), h2
        else:
            def batch_actions(thetas, obs):
                """One env step's policy forward for the whole population."""
                def one(theta, o):
                    return _act(
                        policy_apply(spec.unravel(theta), o.reshape(obs_shape))
                    )
                return jax.vmap(one)(thetas, obs)

            def center_action(params_flat, obs):
                return _act(
                    policy_apply(_params(params_flat), obs.reshape(obs_shape))
                )

        self._batch_actions = jax.jit(batch_actions)  # re-specializes per
        # batch shape, so the same callable serves full and half populations
        self._center_action = jax.jit(center_action)

    def _carries(self, n: int):
        """Stacked episode-start carries for an n-member batch."""
        one = self._carry_init()
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (n,) + x.shape), one
        )

    # ------------------------------------------------------------ interface

    def init_state(self, params_flat, key) -> ESState:
        state = self.core.init_state(params_flat, key)
        if self.obs_norm:
            # same init as the device path: count=1, mean=0, m2=1 → var 1
            d = self.pool.obs_dim
            state = state._replace(obs_stats=(
                jnp.float32(1.0),
                jnp.zeros((d,), jnp.float32),
                jnp.ones((d,), jnp.float32),
            ))
        return state

    # ---- obs_norm host-side helpers ----

    def _norm_params(self, state):
        """(mean, rstd) numpy pair from the state's Welford triple."""
        c, m, m2 = state.obs_stats
        c = float(c)
        mean = np.asarray(m, np.float32)
        var = np.maximum(np.asarray(m2, np.float32) / c, 1e-8)
        return mean, (1.0 / np.sqrt(var)).astype(np.float32)

    def _norm_np(self, obs, mean, rstd):
        return np.clip((obs - mean) * rstd, -self._obs_clip,
                       self._obs_clip).astype(np.float32)

    def compile(self, state: ESState) -> float:
        import time as _time

        t0 = _time.perf_counter()
        pair_offs = self.core.all_pair_offsets(state)
        thetas = self._materialize(state.params_flat, state.sigma, pair_offs)
        # warm the batch size the evaluator will actually use
        warm_n = (
            self.config.population_size // 2
            if self.double_buffer
            else self.config.population_size
        )
        obs = jnp.zeros((warm_n, self.pool.obs_dim), jnp.float32)
        if self.recurrent:
            acts, _ = self._batch_actions(
                thetas[:warm_n], obs, self._carries(warm_n)
            )
            acts.block_until_ready()
        else:
            self._batch_actions(thetas[:warm_n], obs).block_until_ready()
        fwd_dt = _time.perf_counter() - t0
        # the forward warm is a traced-and-executed jit call (its compile
        # can't be split from the warm execution), so its ledger entry
        # carries wall seconds only — the AOT'd update below contributes
        # XLA cost facts via its Compiled object
        self.telemetry.compile_event("pooled_forward", fwd_dt,
                                     first_call=True)
        t1 = _time.perf_counter()
        dummy_w = jnp.zeros((self.config.population_size,), jnp.float32)
        compiled = self.core._apply_weights.lower(state, dummy_w).compile()
        self.telemetry.compile_event(
            "apply_weights", _time.perf_counter() - t1, compiled=compiled,
            first_call=True)
        return _time.perf_counter() - t0

    compile_split = compile

    def member_params(self, state: ESState, member_index: int):
        return self.core.member_params(state, member_index)

    def evaluate(self, state: ESState) -> PooledEvalResult:
        with self.telemetry.phase("sample"):
            pair_offs = self.core.all_pair_offsets(state)
            thetas = self._materialize(state.params_flat, state.sigma,
                                       pair_offs)
            # fence: materialization is device work — unfenced, this span
            # would clock dispatch only and the first batched forward of
            # the step loop would absorb the compute (esguard R07)
            jax.block_until_ready(thetas)
        norm = self._norm_params(state) if self.obs_norm else None
        if self.obs_norm:
            # raw-moment accumulators for this generation's alive steps —
            # merged into the state by apply_weights/generation_step.
            # Stamped with the evaluated state's generation AND its params
            # buffer identity so a discarded evaluation (eval-only probe,
            # exception between the calls) or a DIFFERENT center at the
            # same generation (meta-population NS/NSR/NSRA share gen
            # numbers across centers) can never fold its observations into
            # an unrelated update's running stats — apply_weights drops on
            # any mismatch.
            self._pending_moments = [
                0.0,
                np.zeros(self.pool.obs_dim, np.float64),
                np.zeros(self.pool.obs_dim, np.float64),
            ]
            # hold the buffer itself (not its id()) so the identity can't
            # be recycled by the allocator between the two calls
            self._pending_moments_key = (
                int(state.generation), state.params_flat,
            )
        if self.double_buffer:
            return self._evaluate_double_buffered(thetas, norm)
        return self._evaluate_sync(thetas, norm)

    def _accumulate_moments(self, obs, alive) -> None:
        raw = obs[alive]
        if len(raw):
            m = self._pending_moments
            m[0] += float(len(raw))
            m[1] += raw.sum(axis=0, dtype=np.float64)
            m[2] += (raw.astype(np.float64) ** 2).sum(axis=0)

    def _evaluate_sync(self, thetas, norm=None) -> PooledEvalResult:
        return self._run_pool(
            self.pool, thetas, self.config.population_size, norm,
            accumulate=norm is not None,
        )

    def _run_pool(self, pool, thetas, n, norm, accumulate) -> PooledEvalResult:
        """Step ``n`` episodes (one per pool env, one theta row each) to
        completion: native-thread env stepping + one batched device forward
        per step.  ``accumulate`` feeds the alive observations into the
        pending obs moments (training evaluations only — held-out evals
        must not touch the running stats)."""
        horizon = self.config.horizon

        obs = pool.reset()
        total = np.zeros(n, np.float32)
        alive = np.ones(n, bool)
        final_obs = obs.copy()
        steps = 0
        carry = self._carries(n) if self.recurrent else None
        for _ in range(horizon):
            if norm is not None:
                if accumulate:
                    self._accumulate_moments(obs, alive)
                feed = jnp.asarray(self._norm_np(obs, *norm))
            else:
                feed = jnp.asarray(obs)
            if self.recurrent:
                acts_dev, carry = self._batch_actions(thetas, feed, carry)
                actions = np.asarray(acts_dev)
            else:
                actions = np.asarray(self._batch_actions(thetas, feed))
            next_obs, rew, done = pool.step(actions)
            total += rew * alive
            steps += int(alive.sum())
            # record the observation at termination as the BC frame
            just_died = alive & done
            if just_died.any():
                final_obs[just_died] = obs[just_died]
            alive &= ~done
            obs = next_obs
            if not alive.any():
                break
        final_obs[alive] = obs[alive]  # survivors: last frame
        return PooledEvalResult(
            fitness=total, bc=self._bc(final_obs.copy()), steps=steps
        )

    def _bc(self, final_obs):
        """BC frame → characterization: identity, or the bc_indices dims."""
        return (
            final_obs if self._bc_idx is None else final_obs[..., self._bc_idx]
        )

    def _evaluate_double_buffered(self, thetas, norm=None) -> PooledEvalResult:
        """Overlap device inference with native env stepping (SURVEY.md §7
        hard-part 1).

        The population splits into two halves with independent env pools.
        jax dispatch is asynchronous, so while half A's actions are being
        synced to the host and its envs stepped in C++ threads, half B's
        batched forward is already executing on the device — per step the
        device and the env team work concurrently instead of taking turns.
        Results are identical to running each half through the sync path.
        """
        n = self.config.population_size
        h = n // 2
        horizon = self.config.horizon
        halves = [
            dict(pool=self.pool_a, thetas=thetas[:h], lo=0),
            dict(pool=self.pool_b, thetas=thetas[h:], lo=h),
        ]
        total = np.zeros(n, np.float32)
        alive = np.ones(n, bool)
        steps = 0

        def dispatch(half):
            # NO moment accumulation here: the trailing dispatch after the
            # last stepped iteration computes actions that are never
            # stepped — accumulating at dispatch time would over-count vs
            # the sync path (moments are taken at STEP time below)
            if norm is not None:
                feed = jnp.asarray(self._norm_np(half["obs"], *norm))
            else:
                feed = jnp.asarray(half["obs"])
            if self.recurrent:
                acts, half["carry"] = self._batch_actions(
                    half["thetas"], feed, half["carry"]
                )
                half["fut"] = acts
            else:
                half["fut"] = self._batch_actions(half["thetas"], feed)

        for half in halves:
            half["obs"] = half["pool"].reset()
            if self.recurrent:
                half["carry"] = self._carries(h)
            dispatch(half)
        final_obs = np.concatenate([halves[0]["obs"], halves[1]["obs"]], axis=0)

        for _ in range(horizon):
            if not alive.any():
                break
            for half in halves:
                # syncing this half's actions lets the OTHER half's forward
                # (dispatched at the end of its previous turn) run on-device
                # while this half's envs step in C++ threads
                actions = np.asarray(half["fut"])
                sl = slice(half["lo"], half["lo"] + h)
                if norm is not None:
                    # accumulate exactly the observations that get STEPPED
                    # (pre-step alive mask) — count == env_steps invariant,
                    # identical to the sync path
                    self._accumulate_moments(half["obs"], alive[sl])
                next_obs, rew, done = half["pool"].step(actions)
                total[sl] += rew * alive[sl]
                steps += int(alive[sl].sum())
                just_died = alive[sl] & done
                if just_died.any():
                    final_obs[sl][just_died] = half["obs"][just_died]
                alive[sl] &= ~done
                half["obs"] = next_obs
                dispatch(half)

        for half in halves:
            sl = slice(half["lo"], half["lo"] + h)
            final_obs[sl][alive[sl]] = half["obs"][alive[sl]]
        return PooledEvalResult(
            fitness=total, bc=self._bc(final_obs), steps=steps
        )

    def evaluate_center_batch(
        self, state: ESState, n_episodes: int, seed: int = 0
    ) -> PooledEvalResult:
        """All ``n_episodes`` center-policy episodes in ONE pooled pass
        (round-3 VERDICT weak #6: evaluate_policy ran them serially): a
        fresh n_episodes-env pool steps in native threads while the device
        runs one batched forward per step.  Episode randomness comes from
        the pool seed, so ``seed`` picks the episode set.  Raw moments are
        NOT accumulated — held-out evaluation must not feed the training
        stats.

        The fresh pool per call is deliberate, not an oversight: pools
        seed only on their FIRST reset (see GymVecPool.reset), so caching
        a pool across calls would silently turn "same seed → same episode
        set" into "same seed → wherever the RNG stream got to" — the
        determinism contract held-out comparisons rely on.  The repeated
        ``_batch_actions`` specialization per distinct n_episodes is the
        jit cache working as intended (same shapes hit the cache)."""
        bf16 = self.config.compute_dtype == "bfloat16"
        theta = jnp.asarray(
            state.params_flat, jnp.bfloat16 if bf16 else jnp.float32
        )
        thetas = jnp.broadcast_to(theta, (n_episodes, theta.shape[0]))
        pool = self._make_pool(n_episodes, 0, 20_011 + int(seed))
        norm = self._norm_params(state) if self.obs_norm else None
        try:
            return self._run_pool(pool, thetas, n_episodes, norm,
                                  accumulate=False)
        finally:
            pool.close()

    def evaluate_center(self, state: ESState):
        from ..envs.rollout import RolloutResult

        obs = self.center_pool.reset()[0]
        total, steps = 0.0, 0
        h = self._carry_init() if self.recurrent else None
        norm = self._norm_params(state) if self.obs_norm else None
        for _ in range(self.config.horizon):
            feed = (
                jnp.asarray(self._norm_np(obs[None], *norm)[0])
                if norm is not None else jnp.asarray(obs)
            )
            if self.recurrent:
                a_dev, h = self._center_action(state.params_flat, feed, h)
                a = np.asarray(a_dev)
            else:
                a = np.asarray(self._center_action(state.params_flat, feed))
            nobs, rew, done = self.center_pool.step(a[None])
            total += float(rew[0])
            steps += 1
            if bool(done[0]):
                # post-done nobs[0] is not this episode's frame (C++ pool:
                # fresh reset state; gym pool: terminal obs) — keep the
                # pre-step frame as the BC, matching evaluate()'s convention
                break
            obs = nobs[0]
        return RolloutResult(
            total_reward=jnp.float32(total),
            bc=jnp.asarray(self._bc(np.asarray(obs)), jnp.float32),
            steps=jnp.int32(steps),
        )

    def apply_weights(self, state: ESState, weights):
        new_state, gnorm = self.core.apply_weights(state, jnp.asarray(weights))
        key = self._pending_moments_key
        self._pending_moments_key = None
        if (
            self.obs_norm
            and self._pending_moments is not None
            and key is not None
            and key[0] == int(state.generation)
            and key[1] is state.params_flat
        ):
            # fold the generation's observed raw moments (accumulated by
            # evaluate) into the running Welford triple — the f64 host
            # merge: population×horizon samples per generation would
            # cancel catastrophically in the f32 in-program merge
            from .engine import merge_obs_moments_np

            with self.telemetry.phase("obsnorm_merge"):
                c1, s1, q1 = self._pending_moments
                self._pending_moments = None
                if c1 > 0:
                    new_state = new_state._replace(
                        obs_stats=merge_obs_moments_np(
                            new_state.obs_stats, c1, s1, q1
                        )
                    )
        else:
            # stale moments from a discarded evaluation: drop, never merge
            self._pending_moments = None
        return new_state, gnorm

    def generation_step(self, state: ESState):
        from ..resilience.chaos import mutate_fitness

        obs = self.telemetry
        with obs.phase("eval"):
            ev = self.evaluate(state)
            fit = np.asarray(ev.fitness)
        fit = mutate_fitness(state.generation, fit)
        n_valid = int(np.isfinite(fit).sum())
        base = {"fitness": fit, "bc": ev.bc, "steps": ev.steps,
                "n_valid": n_valid}
        if n_valid < 2:
            # population collapse: report via n_valid with state untouched —
            # ES.train owns the reject/re-run policy (docs/resilience.md)
            return state, {**base, "grad_norm": float("nan"),
                           "update_finite": True}
        # NaN-safe: a crashed/diverged rollout must not win the top rank
        # (np.argsort sorts NaN last) — drop it and renormalize survivors
        with obs.phase("update"):
            weights = rank_weights_with_failures(fit)
            new_state, gnorm = self.apply_weights(state, weights)
            # fence the psum/optax program so the span is device time
            jax.block_until_ready(new_state.params_flat)
        metrics = {
            **base,
            "grad_norm": gnorm,
            # post-update anomaly guard input (ES.train rejects on False)
            "update_finite": bool(
                np.isfinite(np.asarray(gnorm))
                and np.isfinite(np.asarray(new_state.params_flat)).all()
            ),
        }
        return new_state, metrics
