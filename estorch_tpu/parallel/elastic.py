"""Elastic multi-host ES — async importance-weighted folding across hosts.

``multihost.py`` scales by fully-synchronous SPMD: every host steps the
same program and the psum is a fleet-wide barrier, so one straggling
host stalls every generation (and a dead host kills the job).  This
module is the other regime (ROADMAP item 3, docs/multihost.md): hosts
are independent JAX processes — no ``jax.distributed``, no global mesh —
joined to a COORDINATOR over stdlib TCP.  Each host runs the generation
program (replicated, or the PR-7 sharded program over its local mesh) as
an async *source*: the coordinator assigns it whole-population
dispatches, the host evaluates them under the center it was told and
sends back the (population,) fitness contribution, and the scheduler
(algo/scheduler.py::ElasticScheduler) folds arrivals with the clipped
importance weights the worker-level fold already uses — a slow host's
results arrive stale and fold with λ < 1 instead of stalling the fleet;
a dead host's in-flight dispatches are counted ``results_lost`` and
replaced.  Only O(dim) floats cross the wire per update (the center;
never the optimizer state, the noise, or the population).

Membership is ELASTIC: a host may join mid-run (it syncs center +
version from the coordinator and starts contributing — dispatch ids
keep flowing from the coordinator's single counter, so noise
coordinates are never reused) and may leave at any time (TCP EOF is the
death signal; SIGKILL closes the socket).  Every transition lands on
the scheduler's event log (``membership``) and the obs hub
(``hosts_joined``/``hosts_lost`` counters, ``elastic_hosts`` gauge,
per-host ``elastic/h<i>/fold_s`` latency distributions), and
``replay=log`` stays bit-exact because replay is pure math over the
recorded dispatches/updates — membership explains the schedule, it does
not re-drive it.

Wire protocol (every socket operation timed — esguard R17): framed
messages of a JSON header plus raw float32/float64 array payloads;
message types ``join``/``sync``/``center``/``dispatch``/``result``/
``close``.  Chaos (resilience/chaos.py): ``straggle_host`` sleeps in
the host's evaluate loop keyed on (dispatch, host); ``kill_host``
SIGKILLs a subprocess host (a thread-simulated host drops its
connection — same observable death), both on the once-semantics ledger.

Launch recipe (one command per host; docs/multihost.md):

    # coordinator (also the training driver)
    coord = ElasticCoordinator()                 # prints host:port
    es = es_from_spec(spec)                      # device backend
    es.train_elastic(n, fleet=coord)

    # each host, any time before or DURING the run:
    python -m estorch_tpu.parallel.elastic --join HOST:PORT \
        --spec spec.json --host 1
"""

from __future__ import annotations

import json
import os
import queue
import signal
import socket
import struct
import threading
import time

import numpy as np

# bounded slice for every blocking point (accept/recv/inbox get): the
# loops must wake to notice shutdown and dead peers, never sleep
# unbounded (esguard R11/R17 — mechanized as unfenced-cross-host-barrier)
POLL_SLICE_S = 0.05
# sends get their OWN deadline, far above the recv poll slice: the
# socket's 50ms timeout also applies to send(), and a busy-but-alive
# peer (mid-evaluation, not draining) can easily take longer than one
# slice to accept a real model's O(dim) center — only a peer that
# accepts NOTHING for this long is declared dead
SEND_DEADLINE_S = 60.0
PROTO_VERSION = 1
_HDR = struct.Struct(">I")
_MAX_HEADER = 1 << 20


def _socket_close(sock) -> None:
    """Teardown-quiet close (R08: close paths may swallow OSError)."""
    try:
        sock.close()
    except OSError:
        pass


class ElasticError(RuntimeError):
    """Protocol violation or a dead coordinator/host connection."""


class _Killed(Exception):
    """A chaos ``kill_host`` in a thread-simulated host (a subprocess
    host SIGKILLs itself instead)."""


# ---------------------------------------------------------------------
# framed message protocol
# ---------------------------------------------------------------------


def send_msg(sock: socket.socket, header: dict,
             arrays: dict[str, np.ndarray] | None = None,
             deadline_s: float = SEND_DEADLINE_S) -> None:
    """One framed message: 4-byte length + JSON header + raw buffers.
    The header lists ``arrays`` as [name, dtype, shape] so the receiver
    can slice them back without pickling anything.  Sent in timed
    slices against ``deadline_s`` (the socket's own timeout is the recv
    poll slice — one slice is NOT long enough for a large frame to a
    peer that is busy evaluating), raising ``TimeoutError`` when the
    peer accepts nothing for the whole deadline."""
    arrays = arrays or {}
    specs = []
    bufs = []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        specs.append([name, str(arr.dtype), list(arr.shape)])
        bufs.append(arr.tobytes())
    head = json.dumps({**header, "_arrays": specs}).encode()
    view = memoryview(_HDR.pack(len(head)) + head + b"".join(bufs))
    deadline = time.monotonic() + deadline_s
    while view:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"peer not draining ({len(view)} bytes unsent)")
        try:
            sent = sock.send(view)
        except socket.timeout:
            continue  # no buffer space this slice; the deadline bounds us
        view = view[sent:]


def _recv_exact(sock: socket.socket, n: int, deadline: float) -> bytes:
    """Read exactly n bytes in timed slices; raises on EOF or deadline.
    The socket must already carry a timeout (set at connect/accept)."""
    chunks = []
    got = 0
    while got < n:
        if time.monotonic() > deadline:
            raise TimeoutError(f"peer silent mid-message ({got}/{n} bytes)")
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except socket.timeout:
            continue
        if not chunk:
            raise ElasticError("connection closed mid-message")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, timeout_s: float
             ) -> tuple[dict, dict[str, np.ndarray]] | None:
    """One framed message, or None when nothing arrived within
    ``timeout_s`` (the caller's poll slice).  A peer that starts a frame
    must finish it within the message deadline below, so a half-written
    frame cannot wedge the reader (esguard R17)."""
    deadline = time.monotonic() + timeout_s
    head_len = None
    while head_len is None:
        if time.monotonic() > deadline:
            return None
        try:
            first = sock.recv(_HDR.size)
        except socket.timeout:
            continue
        if not first:
            raise ElasticError("connection closed")
        if len(first) < _HDR.size:
            first += _recv_exact(sock, _HDR.size - len(first),
                                 time.monotonic() + 30.0)
        head_len = _HDR.unpack(first)[0]
    if head_len > _MAX_HEADER:
        raise ElasticError(f"oversized header ({head_len} bytes)")
    msg_deadline = time.monotonic() + 60.0
    header = json.loads(_recv_exact(sock, head_len, msg_deadline).decode())
    arrays: dict[str, np.ndarray] = {}
    for name, dtype, shape in header.pop("_arrays", []):
        n_bytes = int(np.dtype(dtype).itemsize * int(np.prod(shape or [1])))
        buf = _recv_exact(sock, n_bytes, msg_deadline)
        arrays[name] = np.frombuffer(buf, dtype=dtype).reshape(shape)
    return header, arrays


# ---------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------


class _HostConn:
    def __init__(self, hid: int, conn: socket.socket):
        self.hid = hid
        self.conn = conn
        self.send_lock = threading.Lock()
        self.inflight: set[int] = set()
        self.alive = True
        self.synced = False  # sync sent — only then routable/broadcast
        self.last_dispatch_t = 0.0


class ElasticCoordinator:
    """Membership + dispatch routing + center broadcast for an elastic
    host fleet.  One instance serves one training driver (usually the
    process calling ``es.train_elastic``); the scheduler talks to it
    through :class:`~estorch_tpu.algo.scheduler._HostSource`.

    Threads: one acceptor (timed ``accept`` loop) plus one reader per
    joined host (timed ``recv`` loop feeding the inbox).  All state
    transitions funnel through the inbox so the scheduler's single
    ``poll`` consumer sees joins/results/leaves in one ordered stream.
    """

    def __init__(self, listen_host: str = "127.0.0.1", port: int = 0,
                 join_grace_s: float = 120.0):
        self.join_grace_s = float(join_grace_s)
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((listen_host, port))
        self._srv.listen(16)
        self._srv.settimeout(POLL_SLICE_S)
        self.address = self._srv.getsockname()
        self._inbox: queue.Queue = queue.Queue()
        self._hosts: dict[int, _HostConn] = {}
        self._lock = threading.Lock()
        self._next_hid = 0
        self._center: np.ndarray | None = None
        self._sigma: float | None = None
        self._version = 0
        self._stop = threading.Event()
        self._threads = [threading.Thread(target=self._acceptor,
                                          daemon=True)]
        self._threads[0].start()

    # ---------------------------------------------------------- center

    def push_center(self, version: int, center: np.ndarray,
                    sigma: float) -> None:
        """One update happened: record it and broadcast the O(dim)
        center to every live host (TCP ordering guarantees a later
        dispatch naming ``version`` finds the center already there)."""
        center = np.asarray(center, np.float32)
        with self._lock:
            self._version = int(version)
            self._center = center.copy()
            self._sigma = float(sigma)
            targets = [h for h in self._hosts.values()
                       if h.alive and h.synced]
        for h in targets:
            self._send(h, {"t": "center", "version": int(version),
                           "sigma": float(sigma)}, {"center": center})

    # -------------------------------------------------------- dispatch

    def n_live(self) -> int:
        with self._lock:
            return sum(1 for h in self._hosts.values()
                       if h.alive and h.synced)

    def dispatch(self, dispatch: int, version: int) -> int | None:
        """Route one dispatch to the least-loaded live host; blocks in
        poll slices up to ``join_grace_s`` for a host to exist (elastic
        start: the driver may begin before the first host finishes its
        jax import).  Returns the host id, or None when the grace
        expired with no live host (the scheduler's dry-out guard turns
        that into a diagnosis)."""
        deadline = time.monotonic() + self.join_grace_s
        while not self._stop.is_set():
            # least loaded first; ties go to the host idle LONGEST.  A
            # fast host answers inside one poll slice, so at decision
            # time every host often shows zero in-flight — a
            # lowest-id tie-break would then starve every other host
            # (and a declared-slow host that never receives work can
            # never exercise the stale fold it exists to absorb)
            with self._lock:
                live = sorted((len(h.inflight), h.last_dispatch_t, h.hid)
                              for h in self._hosts.values()
                              if h.alive and h.synced)
            if live:
                hid = live[0][2]
                with self._lock:
                    h = self._hosts.get(hid)
                    if h is not None and h.alive:
                        h.inflight.add(int(dispatch))
                        h.last_dispatch_t = time.monotonic()
                ok = h is not None and self._send(
                    h, {"t": "dispatch", "dispatch": int(dispatch),
                        "version": int(version)})
                if ok:
                    return hid
                # send failed: mark dead NOW (the reader's leave event
                # still owns the loss/membership accounting) so the next
                # iteration cannot spin on the same corpse — the
                # dispatch was never delivered, try the next host
                with self._lock:
                    if h is not None:
                        h.inflight.discard(int(dispatch))
                        h.alive = False
                continue
            if time.monotonic() > deadline:
                return None
            time.sleep(POLL_SLICE_S)
        return None

    def poll(self, timeout_s: float
             ) -> tuple[list[dict], list[tuple[int, int]], list[dict]]:
        """Drain the inbox: (results, lost (dispatch, host) pairs,
        membership transitions).  One bounded wait, then everything
        already buffered."""
        results: list[dict] = []
        lost: list[tuple[int, int]] = []
        membership: list[dict] = []
        wait = timeout_s
        while True:
            try:
                kind, hid, payload = self._inbox.get(timeout=wait)
            except queue.Empty:
                break
            wait = 0.0
            if kind == "result":
                h = payload.pop("_conn")
                with self._lock:
                    h.inflight.discard(int(payload["dispatch"]))
                results.append(payload)
            elif kind == "join":
                membership.append({"event": "join", "host": hid})
            elif kind == "leave":
                h = payload  # the dying conn (reader enqueues itself)
                with self._lock:
                    pending = sorted(h.inflight)
                    h.alive = False
                    h.inflight.clear()
                lost.extend((d, hid) for d in pending)
                membership.append({"event": "leave", "host": hid})
        return results, lost, membership

    # ------------------------------------------------------- internals

    def _send(self, h: _HostConn, header: dict,
              arrays: dict[str, np.ndarray] | None = None) -> bool:
        try:
            with h.send_lock:
                send_msg(h.conn, header, arrays)
            return True
        except OSError:
            # a failed (or timed-out) send may have left a PARTIAL
            # frame on the wire — the stream is unusable, so a send
            # failure IS the connection's death: close it now (the
            # reader's EOF posts the leave that owns the loss and
            # membership accounting).  The with-block released the
            # send lock on the exception path, so re-take it: other
            # senders racing this one must see alive flip before they
            # try the dead socket
            with h.send_lock:
                h.alive = False
            _socket_close(h.conn)
            return False

    def _acceptor(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(POLL_SLICE_S)
            threading.Thread(target=self._handshake, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn: socket.socket) -> None:
        try:
            got = None
            deadline = time.monotonic() + 30.0
            while got is None:
                if time.monotonic() > deadline or self._stop.is_set():
                    conn.close()
                    return
                got = recv_msg(conn, POLL_SLICE_S)
            header, _ = got
            if header.get("t") != "join":
                conn.close()
                return
        except (ElasticError, OSError, ValueError):
            conn.close()
            return
        with self._lock:
            want = header.get("host")
            hid = int(want) if want is not None else self._next_hid
            while hid in self._hosts and self._hosts[hid].alive:
                hid += 1  # duplicate index → next free (ids stay unique)
            self._next_hid = max(self._next_hid, hid + 1)
            h = _HostConn(hid, conn)
            # reserve the id NOW (two concurrent joins asking for the
            # same index must both see the other's claim); the host
            # stays un-routable and un-broadcast until synced
            self._hosts[hid] = h
            center = self._center
            sync_version = self._version
            sync = {"t": "sync", "host": hid, "proto": PROTO_VERSION,
                    "version": sync_version,
                    "sigma": self._sigma if self._sigma is not None
                    else 0.0}
        # sync BEFORE the host becomes routable: a dispatch can never
        # overtake the center it references (single writer per conn)
        if not self._send(h, sync, {"center": center}
                          if center is not None else None):
            with self._lock:
                if self._hosts.get(hid) is h:
                    del self._hosts[hid]  # release the reservation
            _socket_close(conn)
            return
        # catch the host up to any center its handshake window skipped,
        # BEFORE it becomes routable: a dispatch naming version v must
        # never overtake center v on this connection.  Loop until the
        # version is stable across a send — `h.synced = True` happens
        # under the same lock that reads the version, so a concurrent
        # push_center either already included this host in its broadcast
        # or left a version bump this loop re-sends.  (The seed center
        # keeps version 0 — same as an empty sync — so "host has no
        # center yet" is its own catch-up condition, not a version gap.)
        sent_version = sync_version if center is not None else None
        while True:
            with self._lock:
                cur_version = self._version
                cur_center = self._center
                cur_sigma = self._sigma
                if cur_center is None or sent_version == cur_version:
                    h.synced = True
                    break
            if not self._send(h, {"t": "center",
                                  "version": int(cur_version),
                                  "sigma": float(cur_sigma)},
                              {"center": cur_center}):
                with self._lock:
                    if self._hosts.get(hid) is h:
                        del self._hosts[hid]  # release the reservation
                _socket_close(conn)
                return
            sent_version = cur_version
        self._inbox.put(("join", hid, None))
        t = threading.Thread(target=self._reader, args=(h,), daemon=True)
        self._threads.append(t)
        t.start()

    def _reader(self, h: _HostConn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    got = recv_msg(h.conn, POLL_SLICE_S)
                except (ElasticError, OSError, TimeoutError, ValueError):
                    break
                if got is None:
                    continue
                header, arrays = got
                if header.get("t") == "result":
                    # _conn: like the leave path, the result must settle
                    # its bookkeeping on THE CONNECTION that produced it
                    # — a same-id rejoin may have replaced the table
                    # entry, and discarding on the new conn would leave
                    # the dispatch to be double-counted as lost
                    self._inbox.put(("result", h.hid, {
                        "dispatch": int(header["dispatch"]),
                        "host": h.hid,
                        "fitness": arrays["fitness"],
                        "steps": int(header.get("steps", 0)),
                        "eval_s": float(header.get("eval_s", 0.0)),
                        "_conn": h,
                    }))
                elif header.get("t") == "bye":
                    break
        finally:
            # the leave carries the dying _HostConn itself: a host that
            # died and REJOINED under the same id before this drains
            # must not have its fresh connection killed by the stale
            # leave (poll mutates the payload conn, never the table's)
            self._inbox.put(("leave", h.hid, h))
            _socket_close(h.conn)

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            hosts = list(self._hosts.values())
        for h in hosts:
            self._send(h, {"t": "close"})
            _socket_close(h.conn)
        _socket_close(self._srv)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------
# host worker
# ---------------------------------------------------------------------


class HostWorker:
    """One elastic host: joins a coordinator, evaluates dispatched
    populations with its OWN engine (replicated device, or the PR-7
    sharded generation program when the ES was built ``shard_params=
    True``), and streams back (population,) fitness contributions.

    The worker never sees the optimizer or other hosts — its whole
    world is (center, sigma, version) pushes and dispatch ids; the
    noise regenerates from the shared table via ``(key, dispatch)``
    exactly as on the coordinator."""

    def __init__(self, address: tuple[str, int], es, host_index: int,
                 simulate_kill: bool = False):
        self.address = (str(address[0]), int(address[1]))
        self.es = es
        self.host_index = int(host_index)
        self.simulate_kill = bool(simulate_kill)
        self._stop = threading.Event()
        self._center: np.ndarray | None = None
        self._sigma: float | None = None
        self._version = -1
        self.dispatches_done = 0

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------ run

    def run(self, connect_timeout_s: float = 30.0,
            sync_timeout_s: float = 120.0) -> None:
        import jax.numpy as jnp  # noqa: F401 — the engine is jax-backed

        from ..resilience.chaos import host_fault

        sock = socket.create_connection(self.address,
                                        timeout=connect_timeout_s)
        sock.settimeout(POLL_SLICE_S)
        self._sock = sock
        try:
            send_msg(sock, {"t": "join", "host": self.host_index,
                            "proto": PROTO_VERSION})
            deadline = time.monotonic() + sync_timeout_s
            got = None
            while got is None:
                if time.monotonic() > deadline:
                    raise ElasticError("coordinator never answered JOIN")
                got = recv_msg(sock, POLL_SLICE_S)
            header, arrays = got
            if header.get("t") != "sync":
                raise ElasticError(f"expected sync, got {header.get('t')!r}")
            self.host_index = int(header["host"])
            self._version = int(header["version"])
            if "center" in arrays:
                self._center = np.asarray(arrays["center"], np.float32)
                self._sigma = float(header["sigma"])
            self._warm()
            while not self._stop.is_set():
                try:
                    got = recv_msg(sock, POLL_SLICE_S)
                except (ElasticError, OSError):
                    return  # coordinator gone: the run is over for us
                if got is None:
                    continue
                header, arrays = got
                t = header.get("t")
                if t == "center":
                    self._center = np.asarray(arrays["center"], np.float32)
                    self._sigma = float(header["sigma"])
                    self._version = int(header["version"])
                elif t == "dispatch":
                    d = int(header["dispatch"])
                    if host_fault(d, self.host_index):
                        self._die()
                    fitness, steps, eval_s = self._evaluate(d)
                    try:
                        send_msg(sock, {"t": "result", "dispatch": d,
                                        "steps": int(steps),
                                        "eval_s": float(eval_s)},
                                 {"fitness": np.asarray(fitness,
                                                        np.float32)})
                    except OSError:
                        return  # coordinator gone mid-result: run over
                    self.dispatches_done += 1
                elif t == "close":
                    return
        except _Killed:
            return  # simulated SIGKILL: socket closed abruptly below
        finally:
            _socket_close(sock)

    # ------------------------------------------------------- internals

    def _die(self):
        """kill_host: a subprocess host dies for real (SIGKILL closes
        the socket, which IS the membership-leave signal); a simulated
        (in-thread) host reproduces the observable part — abrupt close."""
        if self.simulate_kill:
            _socket_close(self._sock)
            raise _Killed()
        os.kill(os.getpid(), signal.SIGKILL)

    def _state_for(self, dispatch: int):
        import jax.numpy as jnp

        es = self.es
        if self._center is None:
            raise ElasticError("dispatch before any center sync")
        if getattr(es, "_shard_params", False):
            # the sharded program DONATES its input state — rebuild a
            # fresh one from the synced center each dispatch (the
            # discarded in-program update also consumed the buffers)
            st = es.engine.init_state(jnp.asarray(self._center),
                                      es.state.key)
            return st._replace(
                generation=jnp.asarray(int(dispatch), jnp.int32),
                sigma=jnp.asarray(self._sigma, jnp.float32))
        return es.state._replace(
            params_flat=jnp.asarray(self._center),
            sigma=jnp.asarray(self._sigma, jnp.float32),
            generation=jnp.asarray(int(dispatch), jnp.int32))

    def _evaluate(self, dispatch: int):
        t0 = time.perf_counter()
        es = self.es
        st = self._state_for(dispatch)
        if getattr(es, "_shard_params", False):
            # sharded-program-as-source: run the fused generation and
            # keep only the fitness — the update it computed is the
            # coordinator's job, not ours
            _new, metrics = es.engine.generation_step(st)
            fitness = np.asarray(metrics["fitness"], np.float32)
            steps = int(np.asarray(metrics["steps"]))
        else:
            ev = es.engine.evaluate(st)
            fitness = np.asarray(ev.fitness, np.float32)
            steps = int(np.asarray(ev.steps))
        return fitness, steps, time.perf_counter() - t0

    def _warm(self) -> None:
        """Compile the evaluation program BEFORE accepting dispatches so
        the first real dispatch is not a multi-second compile outlier in
        the coordinator's latency accounting."""
        if self._center is None:
            return
        try:
            self._evaluate(0)
        except Exception:  # noqa: BLE001 — warmth is best-effort
            self.es.obs.event("elastic_warm_failed", host=self.host_index)


def run_host_thread(address: tuple[str, int], es, host_index: int
                    ) -> tuple[HostWorker, threading.Thread]:
    """An in-process 'simulated host' (tests, single-machine demos): its
    own engine instance over the same virtual devices, joined through a
    real loopback socket — everything but the separate interpreter."""
    worker = HostWorker(address, es, host_index, simulate_kill=True)
    t = threading.Thread(target=worker.run, daemon=True,
                         name=f"elastic-host-{host_index}")
    t.start()
    return worker, t


# ---------------------------------------------------------------------
# spec → ES (the subprocess-host / bench entry)
# ---------------------------------------------------------------------


def es_from_spec(spec: dict, mesh=None):
    """Build the demo-family ES a spec JSON names — the shared recipe of
    the coordinator, every subprocess host, and both ``--elastic-ab``
    legs (same seed ⇒ same table ⇒ same noise coordinates everywhere).
    ``mesh`` threads a caller-built device mesh through (the sync-SPMD
    leg passes ``multihost.global_population_mesh()``)."""
    from ..utils.backend import (enable_compilation_cache,
                                 force_cpu_backend)

    if spec.get("cpu_devices"):
        force_cpu_backend(int(spec["cpu_devices"]))
    if spec.get("compilation_cache", True):
        enable_compilation_cache()
    import optax

    from .. import ES, JaxAgent, MLPPolicy
    from .. import envs as envs_mod

    env = getattr(envs_mod, spec.get("env", "CartPole"))()
    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=int(spec.get("population_size", 16)),
        sigma=float(spec.get("sigma", 0.1)),
        policy_kwargs=dict(spec.get("policy_kwargs")
                           or {"action_dim": env.action_dim,
                               "hidden": (8,), "discrete": True}),
        agent_kwargs={"env": env,
                      "horizon": int(spec.get("horizon", 64))},
        optimizer_kwargs={"learning_rate": float(spec.get("lr", 1e-2))},
        seed=int(spec.get("seed", 7)),
        table_size=int(spec.get("table_size", 1 << 18)),
        telemetry=bool(spec.get("telemetry", True)),
    )
    if spec.get("eval_chunk"):
        kw["eval_chunk"] = int(spec["eval_chunk"])
    if spec.get("shard"):
        kw.update(shard_params=True, noise_mode="table")
        if spec.get("model_shards"):
            kw["model_shards"] = int(spec["model_shards"])
    if mesh is not None:
        kw["mesh"] = mesh
    return ES(**kw)


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.parallel.elastic",
        description="join an elastic ES coordinator as one host "
                    "(docs/multihost.md)")
    p.add_argument("--join", required=True, metavar="HOST:PORT")
    p.add_argument("--spec", required=True,
                   help="JSON file (or inline JSON) naming the ES config "
                        "— must match the coordinator's (same seed)")
    p.add_argument("--host", type=int, default=None,
                   help="host index (chaos plans key on it); default: "
                        "coordinator-assigned")
    args = p.parse_args(argv)
    text = args.spec
    if os.path.exists(text):
        with open(text) as f:
            text = f.read()
    spec = json.loads(text)
    es = es_from_spec(spec)
    host, port = args.join.rsplit(":", 1)
    idx = (args.host if args.host is not None
           else 10_000 + (os.getpid() % 10_000))
    worker = HostWorker((host, int(port)), es, idx)
    worker.run()
    print(json.dumps({"host": worker.host_index,
                      "dispatches_done": worker.dispatches_done}),
          flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
