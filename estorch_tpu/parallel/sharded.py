"""Param-sharded hyperscale ES engine — no tree ever whole on one device.

The fused engine (parallel/engine.py) replicates the full param tree on
every device, so the largest trainable policy is capped by one chip's HBM
(ROADMAP open item 1).  This engine implements the "Evolution Strategies
at the Hyperscale" recipe (PAPERS.md, arxiv 2511.16652) on a 2-D
``(pop, model)`` mesh (parallel/mesh.py):

- **Sharded state.**  Params and optimizer state live as TREES whose
  leaves are sharded over ``model`` per regex partition rules
  (:func:`~estorch_tpu.parallel.mesh.match_partition_rules`, SNIPPETS.md
  [1]); optax's param-shaped subtrees resolve through the SAME rules, so
  adam's moments shard exactly like the weights they smooth.
- **In-program noise.**  ε is generated inside the jitted program, keyed
  on ``(key, generation, row, leaf)`` (ops/noise.py ``program_noise``):
  threefry is counter-based, so every mesh shape computes identical
  values while each device materializes only its shard of each (chunked)
  noise block — ε never exists host-side or whole on one device.  With
  ``config.low_rank`` the 2-D leaves where factoring saves draw
  ``A·Bᵀ/√r`` factors instead (ops/lowrank.py
  ``lowrank_program_factors``) and the update einsums the factors — no
  dense E anywhere.  ``noise_mode="table"`` instead slices the classic
  HBM table per leaf (same values as the replicated engine — the
  numerical-parity mode the sharded A/B gates on).
- **Donated on-chip generations.**  ``generation_step`` is ONE jitted
  program with ``donate_argnums=(0,)`` and ``out_shardings`` equal to
  the input state shardings: sample→eval→update runs in place, and the
  only param-sized traffic per generation is the psum'd update GSPMD
  inserts for the weighted-noise contraction — never a replicated tree.

Everything global-view (``jit`` + ``NamedSharding`` constraints, not
``shard_map``): the program is written against full logical shapes and
GSPMD partitions it, which is what makes the numerics mesh-shape
invariant (values identical on (1, N), (N, 1), or (a, b) meshes up to
f32 reduction order — the forward's contractions over model-sharded
dims and the update psum may reassociate, so cross-path comparisons are
``allclose`` at f32, not bit-equal; docs/sharding.md).

Scope: feedforward device-native envs, f32, one episode per member.
obs_norm / decomposed / streamed / noise_kernel / recurrent carries stay
on the replicated engine (their machinery assumes a replicated flat
vector); the ctor rejects them loudly.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..envs.rollout import make_rollout
from ..obs.spans import NULL_TELEMETRY
from ..ops.gradient import fold_mirrored_weights
from ..ops.lowrank import lowrank_program_factors, lowrank_program_leaf_noise
from ..ops.noise import (NoiseTable, leaf_noise_keys, program_noise,
                         row_noise_key, sample_pair_offsets)
from ..ops.params import ParamSpec
from ..ops.ranks import centered_rank_safe
from .engine import EngineConfig, _choose_eval_chunk, _gen_keys
from .mesh import (DEFAULT_PARTITION_RULES, MODEL_AXIS, POP_AXIS,
                   match_partition_rules, padded_count, sharding_summary)

NOISE_MODES = ("program", "table")


def _rng_scope(partitionable: bool):
    """Program-mode dispatch/trace scope: the partitionable threefry
    implementation, without which GSPMD cannot shard in-program normal()
    generation — each device would materialize every FULL noise block as
    a temp, the exact replicate this engine exists to avoid (measured:
    ~1.9× the replicated path's per-device peak at 900k params; with the
    flag it drops well under).  Scoped, not global: the flag changes the
    random stream, and the legacy stream is load-bearing everywhere else
    (the noise table's values are pinned by goldens; table-mode parity
    with the replicated engine needs legacy fold_in/split).  The jit
    trace cache keys on the config, so every dispatch of a program-mode
    computation must re-enter this scope."""
    if partitionable:
        return jax.threefry_partitionable(True)
    import contextlib

    return contextlib.nullcontext()


class ShardedESState(NamedTuple):
    """Training state whose params/opt_state leaves are device-sharded.

    Unlike :class:`~estorch_tpu.parallel.engine.ESState` the params are a
    TREE (sharding is per-leaf, per the partition rules), not a flat
    vector.  ``params_flat`` gathers for host-side consumers (best-member
    snapshots, bundle export, inspection) — it materializes the full
    vector on the default device, so it is an inspection API, not a
    training-path one.
    """

    params: Any  # pytree, leaves sharded per partition rules
    opt_state: Any  # optax state, param-shaped subtrees sharded likewise
    key: jax.Array  # replicated PRNG key (folded with generation)
    generation: jax.Array  # () int32, replicated
    sigma: jax.Array  # () float32, replicated

    @property
    def params_flat(self) -> jax.Array:
        """Gathered flat center vector (ravel_pytree order — identical to
        the replicated path's ``ParamSpec`` layout)."""
        return ravel_pytree(self.params)[0]


class ShardedESEngine:
    """Param-sharded twin of :class:`~estorch_tpu.parallel.engine.ESEngine`.

    Same ``generation_step(state) -> (state, metrics)`` protocol (fitness /
    steps / grad_norm / n_valid / update_finite), so ``ES.train`` drives it
    unchanged.
    """

    telemetry = NULL_TELEMETRY

    def __init__(
        self,
        env: Any,
        policy_apply: Callable[..., Any],
        spec: ParamSpec,
        table: NoiseTable | None,
        optimizer: optax.GradientTransformation,
        config: EngineConfig,
        mesh: Mesh,
        partition_rules=None,
        noise_mode: str = "program",
    ):
        for flag in ("decomposed", "streamed", "noise_kernel", "obs_norm"):
            if getattr(config, flag):
                raise ValueError(
                    f"{flag} is a replicated-engine option; the sharded "
                    "path's noise/state layout replaces it (docs/sharding.md)"
                )
        if config.compute_dtype != "float32":
            raise ValueError(
                "the sharded engine runs in float32 (the parity contract "
                "vs the replicated path is stated at f32)"
            )
        if config.episodes_per_member != 1:
            raise ValueError(
                "episodes_per_member is a replicated-engine option for now")
        if env is None:
            raise ValueError(
                "the sharded engine fuses eval+update on-chip; it has no "
                "update-only mode (use ESEngine for the pooled path)")
        if noise_mode not in NOISE_MODES:
            raise ValueError(
                f"noise_mode must be one of {NOISE_MODES}, got {noise_mode!r}")
        if noise_mode == "table":
            if table is None:
                raise ValueError("noise_mode='table' needs a NoiseTable")
            if config.low_rank:
                raise ValueError(
                    "low_rank noise is generated in-program on the sharded "
                    "path (noise_mode='program'); the table packs full-rank "
                    "rows only"
                )
        missing = {POP_AXIS, MODEL_AXIS} - set(mesh.axis_names)
        if missing:
            raise ValueError(
                f"sharded engine needs a ({POP_AXIS!r}, {MODEL_AXIS!r}) "
                f"mesh (parallel/mesh.py::hyperscale_mesh); {mesh.axis_names} "
                f"is missing {sorted(missing)}"
            )

        self.env = env
        self.policy_apply = policy_apply
        self.spec = spec
        self.table = table
        self.optimizer = optimizer
        self.config = config
        self.mesh = mesh
        self.noise_mode = noise_mode
        self.n_devices = int(mesh.devices.size)
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.pop_shards = int(axis_sizes[POP_AXIS])
        self.model_shards = int(axis_sizes[MODEL_AXIS])
        self.bc_dim = int(env.bc_dim)

        # ---- param-tree layout (tree_flatten order == ravel order) ----
        params_shape = jax.eval_shape(
            spec.unravel, jax.ShapeDtypeStruct((spec.dim,), jnp.float32))
        leaves, self._treedef = jax.tree_util.tree_flatten(params_shape)
        self.leaf_shapes = [tuple(int(d) for d in l.shape) for l in leaves]
        import math

        self.leaf_sizes = [math.prod(s) if s else 1 for s in self.leaf_shapes]
        offs, pos = [], 0
        for sz in self.leaf_sizes:
            offs.append(pos)
            pos += sz
        self.leaf_flat_offsets = offs  # table-mode: leaf start within a row

        # low_rank: which leaves draw factored noise — the SAME
        # (m+n)·r < m·n save-or-dense rule as ops/lowrank.py specs
        self._factored: dict[int, tuple[int, int]] = {}
        if config.low_rank:
            r = int(config.low_rank)
            for i, shape in enumerate(self.leaf_shapes):
                if len(shape) == 2 and r * (shape[0] + shape[1]) < shape[0] * shape[1]:
                    self._factored[i] = (shape[0], shape[1])

        # ---- partition rules → shardings (params + optax state) ----
        self.partition_rules = tuple(
            partition_rules if partition_rules is not None
            else DEFAULT_PARTITION_RULES)
        self.param_shardings = match_partition_rules(
            self.partition_rules, params_shape, mesh)
        opt_shape = jax.eval_shape(optimizer.init, params_shape)
        self.opt_shardings = match_partition_rules(
            self.partition_rules, opt_shape, mesh)
        self._repl = NamedSharding(mesh, P())
        self.state_shardings = ShardedESState(
            params=self.param_shardings,
            opt_state=self.opt_shardings,
            key=self._repl,
            generation=self._repl,
            sigma=self._repl,
        )
        self._param_sharding_leaves = jax.tree_util.tree_leaves(
            self.param_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        # member/row-batched noise blocks: pop axis on the batch dim, the
        # leaf's own spec on the rest
        self._batched_shardings = [
            NamedSharding(mesh, P(POP_AXIS, *sh.spec))
            for sh in self._param_sharding_leaves
        ]

        # ---- population layout (ghost-padded like the replicated path) --
        cfg = config
        if cfg.mirrored:
            if cfg.population_size % 2 != 0:
                raise ValueError(
                    "mirrored sampling needs an even population, got "
                    f"{cfg.population_size}")
            self.rows_global = cfg.population_size // 2
        else:
            self.rows_global = cfg.population_size
        self.members_padded = padded_count(cfg.population_size, self.pop_shards)
        per_shard = self.members_padded // self.pop_shards
        req = max(1, cfg.eval_chunk // self.pop_shards) if cfg.eval_chunk > 0 else 0
        chunk_per_shard = _choose_eval_chunk(req, per_shard)
        self.eval_chunk = chunk_per_shard * self.pop_shards
        self.n_eval_chunks = self.members_padded // self.eval_chunk
        # update reduction chunking over noise rows
        self.rows_padded = padded_count(self.rows_global, self.pop_shards)
        rows_per_shard = self.rows_padded // self.pop_shards
        greq = max(1, cfg.grad_chunk // self.pop_shards) if cfg.grad_chunk > 0 else 0
        gchunk_per_shard = _choose_eval_chunk(greq, rows_per_shard)
        self.grad_chunk = gchunk_per_shard * self.pop_shards
        self.n_grad_chunks = self.rows_padded // self.grad_chunk

        self._rollout = make_rollout(env, policy_apply, cfg.horizon)

        # metrics shardings: scalars/vectors replicated, the in-program
        # best-member tree sharded exactly like the params it perturbs
        metrics_shardings = {
            "fitness": self._repl, "bc": self._repl, "steps": self._repl,
            "grad_norm": self._repl, "n_valid": self._repl,
            "update_finite": self._repl, "sigma": self._repl,
            "best_theta": self.param_shardings,
        }
        # table mode threads the table as a replicated OPERAND, not a
        # closure: a closed-over array lowers as an embedded HLO constant
        # — at table size that bloats the module past the persistent
        # cache's 2 GB proto ceiling and re-uploads per compile
        if noise_mode == "table":
            self._generation_step = jax.jit(
                self._generation_body,
                donate_argnums=(0,),
                in_shardings=(self.state_shardings, self._repl),
                out_shardings=(self.state_shardings, metrics_shardings),
            )
        else:
            self._generation_step = jax.jit(
                lambda state: self._generation_body(state, None),
                donate_argnums=(0,),
                in_shardings=(self.state_shardings,),
                out_shardings=(self.state_shardings, metrics_shardings),
            )
        self._compiled_facts: dict | None = None

    # ------------------------------------------------------------- noise

    def _row_noise(self, i: int, leaf_key, offsets, rows: jax.Array,
                   table_data=None) -> jax.Array:
        """(k, *leaf_shape) noise for leaf ``i`` over row indices ``rows``.

        program mode: generated from the (key, generation, row, leaf)
        chain; table mode: the leaf's slice of each row's table window
        (``table_data`` is the traced operand) — value-identical to the
        replicated engine's ε."""
        shape = self.leaf_shapes[i]
        if self.noise_mode == "table":
            size, loff = self.leaf_sizes[i], self.leaf_flat_offsets[i]
            data = table_data

            def one(row):
                start = offsets[row] + loff
                return jax.lax.dynamic_slice(data, (start,), (size,)).reshape(shape)

            return jax.vmap(one)(rows)
        if i in self._factored:
            m, n = self._factored[i]
            r = int(self.config.low_rank)

            def one(row):
                return lowrank_program_leaf_noise(
                    r, m, n, row_noise_key(leaf_key, row))

            return jax.vmap(one)(rows)
        return jax.vmap(lambda row: program_noise(leaf_key, row, shape))(rows)

    def _leaf_keys(self, okey):
        if self.noise_mode == "table":
            return [None] * len(self.leaf_shapes)
        return leaf_noise_keys(okey, len(self.leaf_shapes))

    def _offsets(self, okey):
        if self.noise_mode != "table":
            return None
        return sample_pair_offsets(
            okey, self.rows_global, self.table.size, self.spec.dim)

    # ------------------------------------------------------------- eval

    def _member_rows_signs(self, ids: jax.Array):
        if self.config.mirrored:
            rows = jnp.minimum(ids // 2, self.rows_global - 1)
            signs = jnp.where(ids % 2 == 0, 1.0, -1.0).astype(jnp.float32)
        else:
            rows = jnp.minimum(ids, self.rows_global - 1)
            signs = jnp.ones(ids.shape, jnp.float32)
        return rows, signs

    def _eval_chunk_body(self, state, offsets, leaf_keys, member_keys, ids,
                         table_data):
        """Evaluate one chunk of (global) member ids: build the chunk's
        perturbed trees leaf-by-leaf (each block sharded (pop, *rule)) and
        vmap the rollout over members."""
        rows, signs = self._member_rows_signs(ids)
        keys = jnp.take(member_keys, rows, axis=0)
        scale = state.sigma * signs  # (chunk,)
        leaves = jax.tree_util.tree_leaves(state.params)
        theta_leaves = []
        for i, leaf in enumerate(leaves):
            eps = self._row_noise(i, leaf_keys[i], offsets, rows, table_data)
            eps = jax.lax.with_sharding_constraint(
                eps, self._batched_shardings[i])
            b = scale.reshape((ids.shape[0],) + (1,) * leaf.ndim)
            theta_leaves.append(leaf[None] + b * eps)
        theta = jax.tree_util.tree_unflatten(self._treedef, theta_leaves)
        res = jax.vmap(self._rollout, in_axes=(0, 0))(theta, keys)
        return res.total_reward, res.bc, res.steps

    def _eval_all(self, state, offsets, leaf_keys, rkey, table_data):
        cfg = self.config
        # rollout keys: one per PAIR when mirrored (common random numbers
        # across the ± twins), one per member otherwise — the replicated
        # engine's exact keying, so table-mode fitness matches it
        member_keys = jax.random.split(rkey, self.rows_global)
        ids = jnp.arange(self.members_padded, dtype=jnp.int32)
        if self.n_eval_chunks == 1:
            f, bc, st = self._eval_chunk_body(
                state, offsets, leaf_keys, member_keys, ids, table_data)
        else:
            def body(_, ids_c):
                return 0, self._eval_chunk_body(
                    state, offsets, leaf_keys, member_keys, ids_c, table_data)

            _, (f, bc, st) = jax.lax.scan(
                body, 0, ids.reshape(self.n_eval_chunks, self.eval_chunk))
            f = f.reshape(self.members_padded)
            bc = bc.reshape(self.members_padded, self.bc_dim)
            st = st.reshape(self.members_padded)
        alive = jnp.arange(self.members_padded) < cfg.population_size
        steps = jnp.where(alive, st, 0).sum()
        return (f[: cfg.population_size], bc[: cfg.population_size], steps)

    # ------------------------------------------------------------- update

    def _weighted_noise_sum(self, state, offsets, leaf_keys, weights,
                            table_data):
        """grad tree = Σ_rows w_row · ε_row / (population · σ), chunked
        over rows; each leaf's accumulator stays sharded like the leaf —
        the contraction over the pop-sharded chunk axis is the ONE psum'd
        param-sized transfer of the generation."""
        cfg = self.config
        if cfg.mirrored:
            row_w = fold_mirrored_weights(weights)  # (rows_global,)
        else:
            row_w = weights
        pad = self.rows_padded - self.rows_global
        rows = jnp.arange(self.rows_padded, dtype=jnp.int32)
        rows = jnp.minimum(rows, self.rows_global - 1)
        if pad:
            row_w = jnp.concatenate([row_w, jnp.zeros((pad,), row_w.dtype)])
        leaves = jax.tree_util.tree_leaves(state.params)
        rank = int(cfg.low_rank) if cfg.low_rank else 0

        def chunk_contrib(i, leaf_key, rows_c, w_c):
            if rank and i in self._factored:
                m, n = self._factored[i]

                def factors(row):
                    return lowrank_program_factors(
                        rank, m, n, row_noise_key(leaf_key, row))

                a, b = jax.vmap(factors)(rows_c)  # (k, m, r), (k, n, r)
                return jnp.einsum(
                    "kmr,knr->mn", a * w_c[:, None, None], b
                ) / jnp.sqrt(jnp.float32(rank))
            eps = self._row_noise(i, leaf_key, offsets, rows_c, table_data)
            eps = jax.lax.with_sharding_constraint(
                eps, self._batched_shardings[i])
            return jnp.tensordot(w_c, eps, axes=1)

        if self.n_grad_chunks == 1:
            acc = [
                jax.lax.with_sharding_constraint(
                    chunk_contrib(i, leaf_keys[i], rows, row_w),
                    self._param_sharding_leaves[i])
                for i in range(len(leaves))
            ]
        else:
            rows_cs = rows.reshape(self.n_grad_chunks, self.grad_chunk)
            w_cs = row_w.reshape(self.n_grad_chunks, self.grad_chunk)

            def body(acc, xs):
                rows_c, w_c = xs
                new = [
                    jax.lax.with_sharding_constraint(
                        acc[i] + chunk_contrib(i, leaf_keys[i], rows_c, w_c),
                        self._param_sharding_leaves[i])
                    for i in range(len(acc))
                ]
                return new, None

            acc0 = [
                jax.lax.with_sharding_constraint(
                    jnp.zeros(self.leaf_shapes[i], jnp.float32),
                    self._param_sharding_leaves[i])
                for i in range(len(leaves))
            ]
            acc, _ = jax.lax.scan(body, acc0, (rows_cs, w_cs))
        denom = jnp.float32(cfg.population_size) * state.sigma
        grad_leaves = [a / denom for a in acc]
        return jax.tree_util.tree_unflatten(self._treedef, grad_leaves)

    # ------------------------------------------------------------- body

    def _generation_body(self, state: ShardedESState, table_data):
        cfg = self.config
        okey, rkey = _gen_keys(state)
        offsets = self._offsets(okey)
        leaf_keys = self._leaf_keys(okey)
        fitness, bc, steps = self._eval_all(
            state, offsets, leaf_keys, rkey, table_data)
        weights, n_valid = centered_rank_safe(fitness)
        grad = self._weighted_noise_sum(
            state, offsets, leaf_keys, weights, table_data)
        if cfg.weight_decay > 0.0:
            grad = jax.tree_util.tree_map(
                lambda g, p: g - cfg.weight_decay * p, grad, state.params)
        gnorm = jnp.sqrt(sum(
            jnp.sum(jnp.square(g)) for g in jax.tree_util.tree_leaves(grad)))
        neg = jax.tree_util.tree_map(jnp.negative, grad)
        updates, new_opt_state = self.optimizer.update(
            neg, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_sigma = state.sigma
        if cfg.sigma_decay != 1.0:
            new_sigma = jnp.maximum(
                state.sigma * cfg.sigma_decay, cfg.sigma_min)
        params_finite = jnp.array(True)
        for leaf in jax.tree_util.tree_leaves(new_params):
            params_finite = jnp.logical_and(
                params_finite, jnp.isfinite(leaf).all())
        update_finite = jnp.logical_and(jnp.isfinite(gnorm), params_finite)
        # In-program anomaly rollback: donation destroys the caller's
        # pre-step buffers, so the restore the replicated path's ES.train
        # does host-side ("reject instead of training on poison",
        # docs/resilience.md) happens HERE — a rejected generation emits
        # the input state unchanged (same generation → the deterministic
        # re-run contract holds) and ES.train only counts/announces it.
        ok = jnp.logical_and(update_finite, n_valid >= 2)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, old)

        new_state = ShardedESState(
            params=keep(new_params, state.params),
            opt_state=keep(new_opt_state, state.opt_state),
            key=state.key,
            generation=jnp.where(ok, state.generation + 1, state.generation),
            sigma=jnp.where(ok, new_sigma, state.sigma),
        )
        # In-program best-member reconstruction: ES.train snapshots the
        # generation's best θ on improvement; with the pre-step center
        # donated it cannot be rebuilt host-side afterwards, so the
        # program emits it — sharded like the params (per-device cost =
        # one extra param shard; the host gathers only on improvement).
        safe_fit = jnp.where(jnp.isfinite(fitness), fitness, -jnp.inf)
        best_rows, best_signs = self._member_rows_signs(
            jnp.argmax(safe_fit)[None])
        best_leaves = []
        for i, leaf in enumerate(jax.tree_util.tree_leaves(state.params)):
            eps = self._row_noise(
                i, leaf_keys[i], offsets, best_rows, table_data)[0]
            best_leaves.append(jax.lax.with_sharding_constraint(
                leaf + state.sigma * best_signs[0] * eps,
                self._param_sharding_leaves[i]))
        metrics = {
            "fitness": fitness,
            "bc": bc,
            "steps": steps,
            "grad_norm": gnorm,
            "n_valid": n_valid,
            "update_finite": update_finite,
            # pre-step σ for the record: ES.train logs prev_state.sigma on
            # the replicated path; that buffer is donated here
            "sigma": state.sigma,
            "best_theta": jax.tree_util.tree_unflatten(
                self._treedef, best_leaves),
        }
        return new_state, metrics

    # ------------------------------------------------------------- public

    def init_state(self, params_flat: jax.Array, key: jax.Array) -> ShardedESState:
        import chex

        chex.assert_shape(params_flat, (self.spec.dim,))
        chex.assert_tree_all_finite(params_flat)
        params = jax.device_put(
            self.spec.unravel(jnp.asarray(params_flat)), self.param_shardings)
        # init the optimizer state ON the mesh: out_shardings places the
        # param-shaped moments without a replicated round-trip
        opt_state = jax.jit(
            self.optimizer.init, out_shardings=self.opt_shardings)(params)
        return ShardedESState(
            params=params,
            opt_state=opt_state,
            key=jax.device_put(key, self._repl),
            generation=jax.device_put(jnp.int32(0), self._repl),
            sigma=jax.device_put(jnp.float32(self.config.sigma), self._repl),
        )

    def compile(self, state: ShardedESState) -> float:
        """AOT-compile the donated generation program; returns seconds.

        The compile ledger entry carries XLA's own per-device argument/
        output/temp byte sizes (``memory_analysis``) — with sharded
        inputs those ARE shard sizes, which is how the bench A/B and the
        acceptance test state per-device peak bytes."""
        import time as _time

        t0 = _time.perf_counter()
        args = (state, self.table.data) if self.noise_mode == "table" else (state,)
        with _rng_scope(self.noise_mode == "program"):
            compiled = self._generation_step.lower(*args).compile()
        dt = _time.perf_counter() - t0
        from ..obs.profile.costmodel import compiled_cost_facts

        self._compiled_facts = compiled_cost_facts(compiled)
        self.telemetry.compile_event("generation_step_sharded", dt,
                                     compiled=compiled, first_call=True)
        return dt

    def memory_facts(self) -> dict:
        """XLA per-device byte facts of the compiled generation program
        ({} before :meth:`compile` or when the jax version hides them)."""
        return dict(self._compiled_facts or {})

    def generation_step(self, state: ShardedESState):
        """Fused sharded ES generation: (new_state, metrics)."""
        if self.noise_mode == "table":
            return self._generation_step(state, self.table.data)
        with _rng_scope(True):
            return self._generation_step(state)

    def member_params(self, state: ShardedESState, member_index: int) -> jax.Array:
        """One member's flat θ (ravel order) — host convenience for
        best-member snapshots (reference's ``best_policy``).

        Computed EAGERLY on the default device from the gathered center:
        the same ``(key, generation, row, leaf)`` noise functions as the
        in-program paths (so the reconstruction is exact), but outside
        the mesh program — a one-member gather is inspection traffic, and
        keeping it off the mesh sidesteps GSPMD resharding of a
        scalar-indexed program for no training-path benefit."""
        with _rng_scope(self.noise_mode == "program"):
            return self._member_params_eager(state, member_index)

    def _member_params_eager(self, state, member_index):
        okey, _ = _gen_keys(state)
        offsets = self._offsets(okey)
        leaf_keys = self._leaf_keys(okey)
        idx = int(member_index)
        if self.config.mirrored:
            row, sign = idx // 2, (1.0 if idx % 2 == 0 else -1.0)
        else:
            row, sign = idx, 1.0
        row = jnp.int32(row)
        table_data = self.table.data if self.noise_mode == "table" else None
        flats = []
        for i, leaf in enumerate(jax.tree_util.tree_leaves(state.params)):
            eps = self._row_noise(
                i, leaf_keys[i], offsets, row[None], table_data)[0]
            flats.append(
                (jax.device_get(leaf) + jax.device_get(
                    state.sigma * sign * eps)).reshape(-1))
        import numpy as np

        return jnp.asarray(np.concatenate(flats))

    def sharding_report(self) -> dict[str, str]:
        """{leaf path: resolved spec} — what the rules did, incl. any
        divisibility fallbacks (manifests, tests, docs examples)."""
        params_shape = jax.eval_shape(
            self.spec.unravel, jax.ShapeDtypeStruct((self.spec.dim,), jnp.float32))
        return sharding_summary(params_shape, self.param_shardings)
