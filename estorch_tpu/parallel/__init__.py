from .engine import EngineConfig, ESEngine, ESState, EvalResult
from .mesh import (
    DEFAULT_PARTITION_RULES,
    MODEL_AXIS,
    POP_AXIS,
    hyperscale_mesh,
    match_partition_rules,
    padded_count,
    pairs_per_device,
    partition_rules_from_json,
    partition_rules_to_json,
    population_mesh,
    single_device_mesh,
)
from .multihost import (
    global_hyperscale_mesh,
    global_population_mesh,
    initialize as initialize_distributed,
    leader_only,
    process_info,
)
from .sharded import ShardedESEngine, ShardedESState

__all__ = [
    "global_hyperscale_mesh",
    "global_population_mesh",
    "initialize_distributed",
    "leader_only",
    "process_info",
    "EngineConfig",
    "ESEngine",
    "ESState",
    "EvalResult",
    "ShardedESEngine",
    "ShardedESState",
    "DEFAULT_PARTITION_RULES",
    "MODEL_AXIS",
    "POP_AXIS",
    "hyperscale_mesh",
    "match_partition_rules",
    "padded_count",
    "pairs_per_device",
    "partition_rules_from_json",
    "partition_rules_to_json",
    "population_mesh",
    "single_device_mesh",
]
