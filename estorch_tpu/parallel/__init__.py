from .engine import EngineConfig, ESEngine, ESState, EvalResult
from .mesh import POP_AXIS, pairs_per_device, population_mesh, single_device_mesh

__all__ = [
    "EngineConfig",
    "ESEngine",
    "ESState",
    "EvalResult",
    "POP_AXIS",
    "pairs_per_device",
    "population_mesh",
    "single_device_mesh",
]
