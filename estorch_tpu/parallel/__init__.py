from .engine import EngineConfig, ESEngine, ESState, EvalResult
from .mesh import POP_AXIS, pairs_per_device, population_mesh, single_device_mesh
from .multihost import (
    global_population_mesh,
    initialize as initialize_distributed,
    leader_only,
    process_info,
)

__all__ = [
    "global_population_mesh",
    "initialize_distributed",
    "leader_only",
    "process_info",
    "EngineConfig",
    "ESEngine",
    "ESState",
    "EvalResult",
    "POP_AXIS",
    "pairs_per_device",
    "population_mesh",
    "single_device_mesh",
]
