"""Device mesh helpers: population data-parallelism + parameter sharding.

The reference's distributed runtime is ``torch.distributed`` gather/broadcast
over ``n_proc`` CPU processes (SURVEY.md §2 item 7).  The TPU-native
equivalent is a 1-D ``jax.sharding.Mesh`` over the available chips with a
single named axis ``POP_AXIS``: each device evaluates its population shard
and the update travels through one ``lax.psum`` riding ICI.  On multi-slice
deployments the same axis spans slices — XLA routes the reduction
hierarchically (ICI within a slice, DCN across) without code changes.

The hyperscale path (parallel/sharded.py, "Evolution Strategies at the
Hyperscale", PAPERS.md arxiv 2511.16652) adds a second axis ``MODEL_AXIS``:
a 2-D ``(pop, model)`` mesh where parameter leaves are sharded over
``model`` per regex partition rules (:func:`match_partition_rules`, the
fmengine/EasyLM idiom — SNIPPETS.md [1]) and the population is sharded
over ``pop``, so neither the param tree nor any member's perturbation
ever exists whole on one device.
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

POP_AXIS = "pop"
MODEL_AXIS = "model"


def population_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """1-D mesh over ``devices`` (default: all) with the population axis."""
    devs = list(devices) if devices is not None else jax.devices()
    return jax.make_mesh((len(devs),), (POP_AXIS,), devices=devs)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    dev = device if device is not None else jax.devices()[0]
    return jax.make_mesh((1,), (POP_AXIS,), devices=[dev])


def hyperscale_mesh(
    pop_shards: int | None = None,
    model_shards: int | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """2-D ``(pop, model)`` mesh for the param-sharded engine.

    Defaults: ``model`` spans every device (maximum per-device memory
    reduction — the hyperscale regime this mesh exists for) and ``pop``
    is the co-factor.  ``pop_shards × model_shards`` must equal the
    device count when both are given.
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if pop_shards is None and model_shards is None:
        pop_shards, model_shards = 1, n
    elif pop_shards is None:
        pop_shards = n // int(model_shards)
    elif model_shards is None:
        model_shards = n // int(pop_shards)
    pop_shards, model_shards = int(pop_shards), int(model_shards)
    if pop_shards * model_shards != n:
        raise ValueError(
            f"mesh shape ({pop_shards}, {model_shards}) needs "
            f"{pop_shards * model_shards} devices, got {n}"
        )
    return jax.make_mesh(
        (pop_shards, model_shards), (POP_AXIS, MODEL_AXIS), devices=devs
    )


def pairs_per_device(population_size: int, n_devices: int) -> int:
    """PADDED antithetic pairs each device owns.

    The population is laid out device-major: device d owns pairs
    [d·k, (d+1)·k) and members [2·d·k, 2·(d+1)·k), so an all_gather of
    per-device fitness reproduces the global member order.

    Pair counts that do not divide the device count are PADDED UP to the
    next multiple: the engine evaluates the padded tail as zero-weighted
    ghost members (clamped noise rows, masked out of the ranking and the
    update — parallel/engine.py), so any even population runs on any
    mesh.  Historically this hard-errored ("use a population that is a
    multiple of 2·n_devices"); the regression test for that case now
    asserts training works.
    """
    if population_size % 2 != 0:
        raise ValueError(f"population_size must be even (mirrored sampling), got {population_size}")
    n_pairs = population_size // 2
    return -(-n_pairs // n_devices)  # ceil division: padded pairs per device


def padded_count(n: int, n_shards: int) -> int:
    """``n`` rounded up to the next multiple of ``n_shards``."""
    return -(-int(n) // int(n_shards)) * int(n_shards)


# ---------------------------------------------------------------------------
# regex partition rules  (SNIPPETS.md [1] `match_partition_rules` idiom)
# ---------------------------------------------------------------------------

# Default rules for the bundled policy families (models/policies.py):
# conv kernels shard their output-channel dim, dense kernels their output
# dim, 1-D vectors (biases, scales, learned carries) shard outright, and
# everything else replicates.  The trailing catch-all makes the defaults
# total over ANY tree; strict user rule sets omit it and get the
# unmatched-leaf error instead.
DEFAULT_PARTITION_RULES = (
    (r"conv[^/]*/kernel$", P(None, None, None, MODEL_AXIS)),
    (r"kernel$", P(None, MODEL_AXIS)),
    (r"(bias|scale|embedding|carry0[^/]*)$", P(MODEL_AXIS)),
    (r".*", P()),
)


def _leaf_path_name(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _fit_spec_to_shape(spec: P, shape, mesh: Mesh) -> P:
    """Drop sharded dims the leaf cannot honor.

    Two fallbacks, both per-dim and both toward replication: a spec
    longer than the leaf's rank keeps only its first ``ndim`` entries,
    and a dim whose size does not divide its mesh-axis extent is
    replicated (jax requires even shards; padding a *parameter* would
    change the optimization problem, so replication is the honest
    fallback — the rule-author sees it via :func:`sharding_summary`).
    """
    ndim = len(shape)
    entries = list(spec)[:ndim]
    entries += [None] * (ndim - len(entries))
    out = []
    for dim, axis in zip(shape, entries):
        if axis is None:
            out.append(None)
            continue
        names = axis if isinstance(axis, tuple) else (axis,)
        extent = 1
        for nm in names:
            extent *= dict(zip(mesh.axis_names, mesh.devices.shape))[nm]
        out.append(axis if dim % extent == 0 else None)
    return P(*out)


def match_partition_rules(rules, tree: Any, mesh: Mesh) -> Any:
    """Pytree of ``NamedSharding`` from ``(regex, PartitionSpec)`` rules.

    Each leaf's '/'-joined tree path is matched against the rules in
    order (``re.search``); the first hit wins.  Scalar leaves (rank 0 or
    a single element) always replicate.  A leaf NO rule matches raises —
    the rule-coverage check that keeps a partial rule set from silently
    replicating a 100M-param leaf.  Works on arrays and
    ``ShapeDtypeStruct``s (so optimizer-state shardings come from
    ``jax.eval_shape`` without materializing anything): optax states
    embed param-shaped subtrees under the same leaf names, so ONE rule
    set covers params and optimizer state (SNIPPETS.md [1]).
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def leaf_sharding(path, leaf):
        name = _leaf_path_name(path)
        shape = tuple(getattr(leaf, "shape", ()))
        size = 1
        for d in shape:
            size *= d
        if len(shape) == 0 or size == 1:
            return NamedSharding(mesh, P())  # never partition scalars
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return NamedSharding(mesh, _fit_spec_to_shape(spec, shape, mesh))
        raise ValueError(
            f"no partition rule matched param leaf '{name}' "
            f"(shape {shape}); add a rule (a trailing ('.*', P()) "
            "replicates unmatched leaves explicitly)"
        )

    return jax.tree_util.tree_map_with_path(leaf_sharding, tree)


def sharding_summary(tree: Any, shardings: Any) -> dict[str, str]:
    """{leaf path: spec} — what the rules actually resolved to (incl.
    divisibility fallbacks), for logs/manifests and the coverage tests."""
    out: dict[str, str] = {}

    def visit(path, leaf, sh):
        out[_leaf_path_name(path)] = str(sh.spec)

    jax.tree_util.tree_map_with_path(visit, tree, shardings)
    return out


def partition_rules_to_json(rules) -> list:
    """Serializable form of a rule set: [[pattern, [dim entries]], ...]
    where a dim entry is an axis name, a list of axis names, or None.
    Round-trips through :func:`partition_rules_from_json` (the config-
    serialization contract the tests pin)."""
    out = []
    for pat, spec in rules:
        entries = []
        for axis in spec:
            if isinstance(axis, tuple):
                entries.append(list(axis))
            else:
                entries.append(axis)
        out.append([pat, entries])
    return out


def partition_rules_from_json(data) -> tuple:
    rules = []
    for pat, entries in data:
        axes = tuple(
            tuple(e) if isinstance(e, list) else e for e in entries
        )
        rules.append((str(pat), P(*axes)))
    return tuple(rules)
