"""Device mesh helpers for population data-parallelism.

The reference's distributed runtime is ``torch.distributed`` gather/broadcast
over ``n_proc`` CPU processes (SURVEY.md §2 item 7).  The TPU-native
equivalent is a 1-D ``jax.sharding.Mesh`` over the available chips with a
single named axis ``POP_AXIS``: each device evaluates its population shard
and the update travels through one ``lax.psum`` riding ICI.  On multi-slice
deployments the same axis spans slices — XLA routes the reduction
hierarchically (ICI within a slice, DCN across) without code changes.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax.sharding import Mesh

POP_AXIS = "pop"


def population_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """1-D mesh over ``devices`` (default: all) with the population axis."""
    devs = list(devices) if devices is not None else jax.devices()
    return jax.make_mesh((len(devs),), (POP_AXIS,), devices=devs)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    dev = device if device is not None else jax.devices()[0]
    return jax.make_mesh((1,), (POP_AXIS,), devices=[dev])


def pairs_per_device(population_size: int, n_devices: int) -> int:
    """Antithetic pairs each device owns; validates divisibility.

    The population is laid out device-major: device d owns pairs
    [d·k, (d+1)·k) and members [2·d·k, 2·(d+1)·k), so an all_gather of
    per-device fitness reproduces the global member order.
    """
    if population_size % 2 != 0:
        raise ValueError(f"population_size must be even (mirrored sampling), got {population_size}")
    n_pairs = population_size // 2
    if n_pairs % n_devices != 0:
        raise ValueError(
            f"population pairs ({n_pairs}) must divide evenly over {n_devices} "
            f"devices; use a population that is a multiple of {2 * n_devices}"
        )
    return n_pairs // n_devices
