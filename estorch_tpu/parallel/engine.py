"""The sharded ES generation engine — one XLA program per generation.

This is the TPU-native replacement for the reference's entire distributed
runtime (SURVEY.md §3.2: Python per-member loop → MPI gather of fitness →
master-only update → parameter broadcast).  Design, per BASELINE.json's
north star:

- **Population DP over a device mesh**: each device owns a contiguous shard
  of antithetic pairs (layout in parallel/mesh.py).  Inside ``shard_map``,
  a ``lax.scan`` over evaluation chunks × ``vmap`` within a chunk rolls out
  every member's episode on-device (envs/rollout.py).
- **No noise on the wire**: every device derives the SAME pair offsets from
  the replicated ``(key, generation)`` via a counter-based PRNG and slices
  its shard by ``axis_index`` — ε is regenerated locally from the shared
  table (ops/noise.py).
- **One small all_gather + one psum**: fitness (O(population) floats) is
  all-gathered so every device computes identical centered ranks; the
  rank-weighted noise sum is reduced with a single ``lax.psum`` riding ICI.
- **No parameter broadcast**: the psum result — and hence the optax update —
  is bit-identical on every device, so parameters stay replicated by
  construction.  This deletes the reference's broadcast entirely.

Two entry points share all machinery:
  * ``generation_step`` — fused evaluate+rank+update for vanilla ES.
  * ``evaluate`` / ``apply_weights`` — the split path for the novelty family
    (NS/NSR/NSRA), whose rank weights depend on a host-side archive.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from ..envs.rollout import carry_init_takes_params, make_obs_probe, make_rollout
from ..obs.spans import NULL_TELEMETRY
from ..utils.backend import shard_map
from ..ops.gradient import es_gradient, rank_weighted_noise_sum
from ..ops.noise import NoiseTable, member_offsets, pair_signs, sample_pair_offsets
from ..ops.params import ParamSpec
from ..ops.ranks import centered_rank_safe
from .mesh import POP_AXIS, padded_count, pairs_per_device


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static engine configuration (hashable; closed over at trace time)."""

    population_size: int
    sigma: float
    horizon: int
    eval_chunk: int = 0  # members per rollout chunk; 0 → whole local shard
    grad_chunk: int = 256  # noise rows (pairs when mirrored, members when
    # not) per gradient-reduction chunk
    weight_decay: float = 0.0  # L2 pull toward 0, applied with the update
    compute_dtype: str = "float32"  # "bfloat16" runs the POLICY forward in
    # bf16 (MXU-native, half the HBM traffic for the per-member weights);
    # params, noise table, env dynamics, and the update stay float32
    sigma_decay: float = 1.0  # per-generation multiplicative σ annealing
    sigma_min: float = 0.0  # σ floor when annealing
    mirrored: bool = True  # antithetic pairs (variance reduction — kept on
    # by default everywhere, incl. the bundled configs). Set False for the
    # reference's plain per-member sampling (supported on all backends).
    episodes_per_member: int = 1  # rollouts averaged per member (device
    # path only): reduces fitness noise AND raises per-step batch (n·e rows
    # through the policy matmuls — better MXU use for small populations)
    decomposed: bool = False  # z = x@W + c(x@E): the shared-W term of every
    # layer becomes ONE population-wide dense matmul (W un-batched under
    # vmap) instead of per-member matvecs against materialized perturbed
    # weights; needs a decomposed_apply (models/decomposed.py)
    noise_kernel: bool = False  # Pallas streamed update reduction
    # (ops/pallas_noise.py): ε rows DMA'd from the HBM table through
    # double-buffered VMEM and FMA'd in place — no (chunk, dim)
    # materialization. Interpret-mode off-TPU, Mosaic on-chip.
    low_rank: int = 0  # >0: per-layer kernel noise E = A·Bᵀ/√r with r =
    # low_rank (ops/lowrank.py, PAPERS.md "ES at the Hyperscale"): member
    # noise state shrinks O(dim) → O(Σ(m+n)·r), the forward's noise term
    # O(m·n) → O((m+n)·r) per step, and the update is one einsum per layer
    # over the population.  Approximates isotropic ES (exact for biases);
    # mutually exclusive with decomposed/streamed/noise_kernel.
    streamed: bool = False  # Pallas streamed FORWARD: the decomposed
    # population forward with every layer's ε tiles DMA'd from the table —
    # no member's noise tree is ever materialized, so resident noise bytes
    # drop from O(population·dim) to O(2·tile). Implies a population-
    # batched rollout (one policy call per step for the whole local shard).
    # Needs a streamed_apply (ES builds it for MLPPolicy); f32 only.
    obs_norm: bool = False  # running observation normalization (the
    # OpenAI-ES MuJoCo staple the reference never had): every policy input
    # is (obs - mean)·rsqrt(var) clipped to ±obs_clip, with the running
    # raw-obs moments carried in ESState.obs_stats and refreshed each
    # generation from obs_probe_episodes center-policy episodes — fully
    # in-program, replicated on every device. Composes with every noise
    # representation (standard/recurrent/decomposed/streamed/low_rank):
    # normalization is an input-side transform, applied to raw obs in f32
    # before any forward. NOTE the stats-refresh data source differs by
    # backend: the device path feeds obs_stats from center-policy probe
    # episodes only, while the pooled path folds in every member's
    # (perturbed-policy) observations — both are self-consistent and
    # checkpoint-compatible, but a run migrated across paths resumes with
    # differently-converged normalization statistics.
    obs_clip: float = 5.0  # normalized-obs clip range
    obs_probe_episodes: int = 1  # center episodes per generation feeding
    # the running stats (more → faster stat convergence, more probe FLOPs)
    obs_warmup_episodes: int = 0  # >0: run this many init-policy probe
    # episodes at init_state so generation 0 already normalizes with real
    # moments instead of the identity init (round-4 A/B: the identity
    # init costs early-generation AUC while the stats converge; warmup
    # removes that transient). Device path only — the pooled path's
    # stats are fed by every member's observations from generation 0
    # onward, so its transient is one generation long already.


class ESState(NamedTuple):
    """Replicated across devices; everything needed to resume exactly."""

    params_flat: jax.Array  # (dim,) float32 — center of the search distribution
    opt_state: Any
    key: jax.Array  # PRNG key, folded with generation for per-gen streams
    generation: jax.Array  # () int32
    sigma: jax.Array  # () float32 — current perturbation scale (annealable)
    obs_stats: Any = None  # obs_norm only: (count, mean, m2) running
    # raw-observation moments in Welford form — mean and m2/count stay O(1)
    # magnitude forever, so no f32 cancellation or accumulator saturation
    # however long the run (naive sum/sumsq would cancel catastrophically
    # on dims with |mean| >> std, exactly the locomotion case obs_norm
    # exists for)


def normalize_obs(obs: jax.Array, obs_stats, clip: float) -> jax.Array:
    """(obs − mean)·rsqrt(var), clipped — the obs_norm transform.

    ``obs_stats`` is the (count, mean, m2) Welford triple (var = m2/count);
    variance is floored at 1e-8 so fresh stats (var≈1 at init) and
    constant dimensions stay finite."""
    cnt, mean, m2 = obs_stats
    var = jnp.maximum(m2 / cnt, 1e-8)
    x = (obs.astype(jnp.float32) - mean) * jax.lax.rsqrt(var)
    return jnp.clip(x, -clip, clip)


def merge_obs_moments_np(obs_stats, cnt1: float, osum1, osumsq1):
    """Host-side float64 Chan merge for POOLED-scale raw sums.

    The in-program f32 merge below is safe only for a few episodes' worth
    of samples; the pooled path accumulates population×horizon steps per
    generation, where ``sumsq − sum·mean`` cancels catastrophically in
    f32 (e.g. c≈1e6 at mean≈100: the f32 ulp of sumsq exceeds the true
    m2).  Merge in f64, hand back an f32 jnp triple for the state."""
    import numpy as np

    # Precision bound: the merge itself is f64-exact, but the count is
    # handed back as f32 for the ESState schema, so past 2^24 (~16.7M)
    # samples the STORED count rounds (ulp 2 at 2^25, …).  mean/m2 keep
    # full f64 accuracy — only the count's least bits are lost, a ≤2^-24
    # relative error in the next merge's weights.  At pooled scale
    # (pop 256 × horizon 1000 → 2^24 in ~65 generations) the documented
    # "count == 1 + env_steps" invariant therefore holds exactly only
    # below 2^24 total samples; beyond it the stats keep converging
    # correctly but the count is a rounded f32.
    c0 = float(np.asarray(obs_stats[0]))
    m0 = np.asarray(obs_stats[1], np.float64)
    M0 = np.asarray(obs_stats[2], np.float64)
    c1 = float(cnt1)
    s1 = np.asarray(osum1, np.float64)
    q1 = np.asarray(osumsq1, np.float64)
    mean1 = s1 / c1
    m2_1 = np.maximum(q1 - s1 * mean1, 0.0)
    tot = c0 + c1
    delta = mean1 - m0
    mean = m0 + delta * (c1 / tot)
    m2 = M0 + m2_1 + delta * delta * (c0 * c1 / tot)
    return (
        jnp.float32(tot),
        jnp.asarray(mean, jnp.float32),
        jnp.asarray(m2, jnp.float32),
    )


def merge_obs_moments(obs_stats, cnt1, osum1, osumsq1):
    """Chan parallel update: fold one generation's raw probe sums (small —
    a few episodes' worth, safe in f32) into the running Welford triple.
    For pooled-scale sums use :func:`merge_obs_moments_np`.

    Saturation bound of the all-f32 device-path merge: the running count
    stops incrementing once cnt1 < ulp(count)/2, i.e. count ≳ cnt1·2^24 —
    at the device path's few-episode probes (cnt1 ≈ 100-1000) that is
    ~10^9-10^10 samples, far past any recorded run; the update weight
    already decays as cnt1/count long before, so the frozen tail is
    benign.  The pooled path never hits this (its merge is
    :func:`merge_obs_moments_np`, f64 on the host)."""
    c0, mean0, m2_0 = obs_stats
    mean1 = osum1 / cnt1
    m2_1 = jnp.maximum(osumsq1 - osum1 * mean1, 0.0)
    tot = c0 + cnt1
    delta = mean1 - mean0
    mean = mean0 + delta * (cnt1 / tot)
    m2 = m2_0 + m2_1 + delta * delta * (c0 * cnt1 / tot)
    return tot, mean, m2


class EvalResult(NamedTuple):
    fitness: jax.Array  # (population,) float32, global member order
    bc: jax.Array  # (population, bc_dim) float32
    steps: jax.Array  # () int32 — total alive env steps this generation


def _gen_keys(state: ESState) -> tuple[jax.Array, jax.Array]:
    """Per-generation streams: (offset key, rollout key). Identical everywhere."""
    base = jax.random.fold_in(state.key, state.generation)
    return jax.random.fold_in(base, 0), jax.random.fold_in(base, 1)


def _cast_leaves(tree, dtype):
    return jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype), tree)


def _check_bf16_params(p) -> None:
    """Trace-time contract check (zero runtime cost): a caller that forgot
    the once-per-member cast would otherwise silently run the rollout in
    f32 (bf16 obs × f32 weights promotes) — losing the perf this path
    exists for with no error anywhere."""
    bad = sorted(
        {
            str(leaf.dtype)
            for leaf in jax.tree_util.tree_leaves(p)
            if jnp.issubdtype(leaf.dtype, jnp.floating)
            and leaf.dtype != jnp.bfloat16
        }
    )
    if bad:
        raise TypeError(
            f"bf16 compute path was handed {bad} params; cast the member "
            "tree once where it is built (ESEngine._member_cast / pooled "
            "materialize) before calling policy_apply"
        )


def _bf16_obs(obs):
    """Floating observations cast to bf16 (integer pixel bytes pass through
    so the policy's own normalization still fires)."""
    if jnp.issubdtype(obs.dtype, jnp.floating):
        return obs.astype(jnp.bfloat16)
    return obs


def _bf16_io_apply(base_apply):
    """Observation/output dtype shim for the bf16 compute path.  Params must
    ALREADY be bf16 — they are cast ONCE per member where they are built
    (``_eval_local`` / center eval), never inside the per-step rollout scan,
    so the steady-state episode loop is cast-free (round-1 VERDICT weak #6:
    the old wrapper re-cast the whole weight pytree every policy call and
    relied on XLA CSE to hoist it).  Output returns to float32."""

    def wrapped(p, obs):
        _check_bf16_params(p)
        return base_apply(p, _bf16_obs(obs)).astype(jnp.float32)

    return wrapped


def _bf16_io_apply_stateful(base_apply):
    """Recurrent twin of :func:`_bf16_io_apply`: the hidden carry stays
    bf16 across the whole scan (the engine casts ``carry_init`` once), so
    no per-step carry casts exist — only the obs in / action out shims."""

    def wrapped(p, obs, h):
        _check_bf16_params(p)
        out, h_new = base_apply(p, _bf16_obs(obs), h)
        return out.astype(jnp.float32), h_new

    return wrapped


def _choose_eval_chunk(requested: int, local_members: int) -> int:
    """Largest divisor of ``local_members`` that is ≤ the requested chunk."""
    if requested <= 0 or requested >= local_members:
        return local_members
    c = min(requested, local_members)
    while local_members % c != 0:
        c -= 1
    return c


NOISE_KERNEL_MAX_DIM = 1_000_000  # 3·dim f32 ≈ 12 MiB of ~16 MiB v5e VMEM


class ESEngine:
    """Compiles and caches the per-generation XLA programs for one setup."""

    # span telemetry hub; ES replaces this with its own (obs/spans.py).
    # The fused generation program cannot be phase-split host-side — the
    # engine's contributions are compile events + recompile counters
    telemetry = NULL_TELEMETRY

    def __init__(
        self,
        env: Any,
        policy_apply: Callable[..., Any],  # (p, obs) -> out, or the
        # recurrent (p, obs, carry) -> (out, carry') form when carry_init
        # is given
        spec: ParamSpec,
        table: NoiseTable,
        optimizer: optax.GradientTransformation,
        config: EngineConfig,
        mesh: Mesh,
        decomposed_apply=None,
        streamed_apply=None,
        lowrank_apply=None,
        lowrank_spec=None,
        carry_init=None,
    ):
        self.env = env
        if carry_init is not None and (config.decomposed or config.streamed):
            # these paths restructure the FORWARD around the MLP layer
            # identity (models/decomposed.py) and have no recurrent form.
            # low_rank composes: the tree form (ops/lowrank.py) materializes
            # each member's perturbation once per episode and runs the
            # standard carry-threaded rollout
            raise ValueError(
                "recurrent policies run the standard forward; they are "
                "mutually exclusive with decomposed/streamed"
            )
        if config.obs_norm:
            if env is None:
                raise ValueError(
                    "obs_norm needs device-native rollouts to carry the "
                    "running stats in-program; it is a device-path option"
                )
        if config.low_rank:
            if config.decomposed or config.streamed or config.noise_kernel:
                raise ValueError(
                    "low_rank replaces the full-rank noise pathway; it is "
                    "mutually exclusive with decomposed/streamed/noise_kernel"
                )
            if lowrank_spec is None or (
                lowrank_apply is None and env is not None and carry_init is None
            ):
                # recurrent policies need no lowrank_apply: they perturb via
                # lowrank_tree_perturb and run the standard rollout
                raise ValueError(
                    "EngineConfig.low_rank needs lowrank_apply + lowrank_spec "
                    "(ops/lowrank.py; ES builds them for MLPPolicy)"
                )
        self.lr_spec = lowrank_spec if config.low_rank else None
        # the per-member noise vector the table serves: full-rank ε is (dim,),
        # low-rank is the packed (A‖B‖bias) factors — everything that samples
        # offsets or slices noise uses THIS, not spec.dim
        self.noise_dim = (
            self.lr_spec.noise_dim if config.low_rank else spec.dim
        )
        if config.decomposed and decomposed_apply is None and env is not None:
            raise ValueError(
                "EngineConfig.decomposed=True needs a decomposed_apply "
                "(models/decomposed.py::mlp_decomposed_apply for MLPPolicy)"
            )
        if config.streamed:
            if config.decomposed:
                raise ValueError(
                    "streamed IS the kernel form of decomposed — enable one"
                )
            if config.episodes_per_member != 1:
                raise ValueError(
                    "streamed currently supports episodes_per_member=1"
                )
            if config.compute_dtype != "float32":
                raise ValueError(
                    "streamed runs in float32 (the table and kernel are f32)"
                )
            if streamed_apply is None and env is not None:
                raise ValueError(
                    "EngineConfig.streamed=True needs a streamed_apply "
                    "(ops/pallas_noise.py::mlp_streamed_apply for MLPPolicy)"
                )
        self._streamed_apply = streamed_apply
        if config.noise_kernel and spec.dim > NOISE_KERNEL_MAX_DIM:
            # weighted_noise_sum holds 3·dim f32 in VMEM (double buffer +
            # accumulator, ops/pallas_noise.py) — past ~1M params that blows
            # the ~16 MiB v5e VMEM budget as an opaque Mosaic error, so fail
            # loudly here instead (chunked pure-JAX reduction handles any dim)
            raise ValueError(
                f"noise_kernel=True supports up to {NOISE_KERNEL_MAX_DIM:,} "
                f"params (3·dim f32 must fit VMEM); got dim={spec.dim:,}. "
                "Drop noise_kernel to use the chunked pure-JAX reduction."
            )
        if config.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be float32 or bfloat16, got {config.compute_dtype!r}"
            )
        if config.episodes_per_member < 1:
            raise ValueError(
                f"episodes_per_member must be >= 1, got {config.episodes_per_member}"
            )
        self._bf16 = config.compute_dtype == "bfloat16"
        if self._bf16:
            if carry_init is not None:
                policy_apply = _bf16_io_apply_stateful(policy_apply)
                # cast the episode-start carry ONCE so the scan carry dtype
                # is bf16 throughout (a f32 init would flip dtypes between
                # scan iterations); forward params only to the params-aware
                # form — the legacy zero-arg form (still supported by
                # make_rollout's detection) must keep working under bf16
                base_carry_init = carry_init
                _ci_takes_params = carry_init_takes_params(base_carry_init)
                carry_init = lambda params=None: _cast_leaves(
                    base_carry_init(params) if _ci_takes_params
                    else base_carry_init(), jnp.bfloat16)
            else:
                policy_apply = _bf16_io_apply(policy_apply)
        self._carry_init = carry_init

        self.policy_apply = policy_apply
        self.spec = spec
        self.table = table
        self.optimizer = optimizer
        self.config = config
        self.mesh = mesh
        self.n_devices = mesh.devices.size
        # Populations whose pair/member count does not divide the mesh are
        # PADDED up to the next multiple with zero-weighted ghost members:
        # ghosts re-evaluate clamped noise rows (values irrelevant), are
        # sliced out of the gathered fitness before ranking, and their
        # rank weights are zero-padded before the update slice — so they
        # cannot move the parameters.  rows_* is the noise-row structure
        # (pairs when mirrored, members otherwise); only the REAL row
        # count is ever sampled from the table, so a padded run's noise
        # stream is identical to the same population on a dividing mesh.
        if config.mirrored:
            self.pairs_local = pairs_per_device(config.population_size, self.n_devices)
            self.members_local = 2 * self.pairs_local
            self.rows_global = config.population_size // 2
            self.rows_padded = self.pairs_local * self.n_devices
        else:
            self.pairs_local = None  # unmirrored: no pair structure
            self.members_local = (
                padded_count(config.population_size, self.n_devices)
                // self.n_devices
            )
            self.rows_global = config.population_size
            self.rows_padded = self.members_local * self.n_devices
        self.members_padded = self.members_local * self.n_devices
        self.eval_chunk = _choose_eval_chunk(config.eval_chunk, self.members_local)

        self._obs_norm = config.obs_norm  # always False when env is None
        # (the guard above rejects obs_norm for update-only engines)
        if env is None:
            # update-only mode: the evaluation happens elsewhere (e.g. the
            # pooled host-env path, parallel/pooled.py) and only the
            # offset-derivation + psum-update programs are built
            self.bc_dim = 0
            self._rollout = None
            self._build_update_programs()
            return
        self.bc_dim = int(env.bc_dim)

        # obs_norm: every rollout's apply takes (params, obs_stats) packed —
        # the running stats ride the SAME traced state the params do, so the
        # whole generation (members + probe + center eval) normalizes with
        # one consistent snapshot
        rollout_apply = policy_apply
        rollout_carry_init = carry_init
        if config.obs_norm:
            clip = float(config.obs_clip)
            base_apply = policy_apply
            if carry_init is not None:
                def rollout_apply(packed, obs, h):
                    p, stats = packed
                    return base_apply(p, normalize_obs(obs, stats, clip), h)

                # the rollout's "params" are the packed (params, obs_stats)
                # pair — a learned episode-start carry must read from the
                # PARAMS half (models/policies.py learned_carry)
                base_ci = carry_init

                def rollout_carry_init(packed=None):
                    return base_ci(None if packed is None else packed[0])
            else:
                def rollout_apply(packed, obs):
                    p, stats = packed
                    return base_apply(p, normalize_obs(obs, stats, clip))

        self._rollout = make_rollout(
            env, rollout_apply, config.horizon, carry_init=rollout_carry_init
        )
        self._obs_probe = (
            make_obs_probe(env, rollout_apply, config.horizon,
                           carry_init=rollout_carry_init)
            if config.obs_norm else None
        )

        self._rollout_batched = None
        if config.streamed:
            from ..envs.rollout import make_batched_rollout

            self._rollout_batched = make_batched_rollout(env, config.horizon)

        self._rollout_lowrank = None
        if config.low_rank and carry_init is None:
            # the MLP per-step factored form; recurrent low_rank reuses
            # self._rollout on per-episode-materialized trees instead
            def lr_packed_apply(packed, obs):
                shared, lrn, c = packed
                return lowrank_apply(shared, lrn, c, obs)

            if self._bf16:
                lr_packed_apply = _bf16_io_apply(lr_packed_apply)

            if config.obs_norm:
                # normalization wraps OUTSIDE the bf16 shim: raw obs are
                # normalized in f32 against the generation's stats snapshot,
                # then cast — the same order as the standard path above
                base_lr_apply = lr_packed_apply

                def lr_packed_apply(packed, obs):
                    inner, stats = packed
                    return base_lr_apply(inner, normalize_obs(obs, stats, clip))

            self._rollout_lowrank = make_rollout(env, lr_packed_apply, config.horizon)

        self._rollout_decomposed = None
        if config.decomposed:
            def packed_apply(packed, obs):
                shared, noise, c = packed
                return decomposed_apply(shared, noise, c, obs)

            if self._bf16:
                # packed (shared, noise, c) params — INCLUDING the scale c —
                # arrive pre-cast from _eval_local; only obs/output shim here
                packed_apply = _bf16_io_apply(packed_apply)

            if config.obs_norm:
                base_dec_apply = packed_apply

                def packed_apply(packed, obs):
                    inner, stats = packed
                    return base_dec_apply(inner, normalize_obs(obs, stats, clip))

            self._rollout_decomposed = make_rollout(
                env, packed_apply, config.horizon
            )

        # All inputs/outputs are fully replicated (P()); the population axis
        # only exists INSIDE the program (axis_index-derived shards).
        self._generation_step = jax.jit(
            shard_map(
                self._generation_body,
                mesh=mesh,
                in_specs=(P(),),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )
        # split path: evaluate, then apply host-computed weights
        self._evaluate = jax.jit(
            shard_map(
                self._evaluate_body,
                mesh=mesh,
                in_specs=(P(),),
                out_specs=P(),
                check_vma=False,
            )
        )
        self._build_update_programs()

        def center_eval(state: ESState):
            _, rkey = _gen_keys(state)
            ckey = jax.random.fold_in(rkey, 2**31 - 1)  # stream disjoint from members
            params = self._member_cast(self.spec.unravel(state.params_flat))
            if self._obs_norm:
                params = (params, state.obs_stats)
            return self._rollout(params, ckey)

        # evaluates the unperturbed center policy (reference's `es.policy`):
        # used for best-snapshot logging and the novelty family's archive BCs
        self._center_eval = jax.jit(center_eval)

    def _build_update_programs(self):
        self._apply_weights = jax.jit(
            shard_map(
                self._apply_weights_body,
                mesh=self.mesh,
                in_specs=(P(), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

    def all_pair_offsets(self, state: ESState) -> jax.Array:
        """The full per-PAIR (mirrored) or per-MEMBER (unmirrored) offset
        vector for this generation — the same derivation every device
        performs inside the update program, so external evaluators (pooled
        path) perturb with identical noise."""
        okey, _ = _gen_keys(state)
        n = (
            self.config.population_size // 2
            if self.config.mirrored
            else self.config.population_size
        )
        return sample_pair_offsets(okey, n, self.table.size, self.noise_dim)

    def _member_cast(self, tree):
        """bf16 path: cast a member's param tree once, where it is built."""
        return _cast_leaves(tree, jnp.bfloat16) if self._bf16 else tree

    # ---- shard-local bodies (run once per device under shard_map) ----

    def _local_offsets_signs_keys(self, state: ESState):
        """This device's (reduction offsets, member offsets, signs, keys).

        Mirrored: one table offset per antithetic pair; member offsets repeat
        it, signs alternate, and pair members share a rollout key (common
        random numbers).  Unmirrored (reference's plain ES): one independent
        offset and key per member, all signs +1; the reduction offsets ARE
        the member offsets.
        """
        cfg = self.config
        okey, rkey = _gen_keys(state)
        d = jax.lax.axis_index(POP_AXIS)
        if cfg.mirrored:
            all_pair_offsets = self._pad_rows(sample_pair_offsets(
                okey, cfg.population_size // 2, self.table.size, self.noise_dim
            ))
            pair_offs = jax.lax.dynamic_slice(
                all_pair_offsets, (d * self.pairs_local,), (self.pairs_local,)
            )
            member_offs = member_offsets(pair_offs)
            signs = pair_signs(self.members_local)
            pair_keys = self._pad_rows(
                jax.random.split(rkey, cfg.population_size // 2))
            local_pair_keys = jax.lax.dynamic_slice(
                pair_keys, (d * self.pairs_local, 0), (self.pairs_local, pair_keys.shape[1])
            )
            member_keys = jnp.repeat(local_pair_keys, 2, axis=0)
            return pair_offs, member_offs, signs, member_keys
        all_offsets = self._pad_rows(sample_pair_offsets(
            okey, cfg.population_size, self.table.size, self.noise_dim
        ))
        member_offs = jax.lax.dynamic_slice(
            all_offsets, (d * self.members_local,), (self.members_local,)
        )
        signs = jnp.ones((self.members_local,), jnp.float32)
        keys = self._pad_rows(jax.random.split(rkey, cfg.population_size))
        member_keys = jax.lax.dynamic_slice(
            keys, (d * self.members_local, 0), (self.members_local, keys.shape[1])
        )
        return member_offs, member_offs, signs, member_keys

    def _pad_rows(self, x: jax.Array) -> jax.Array:
        """Pad a per-row array (offsets / pair keys) to the padded row
        count by repeating row 0 — ghost rows carry zero weight in every
        reduction, so the clamped values are never observable."""
        pad = self.rows_padded - self.rows_global
        if pad == 0:
            return x
        ghost = jnp.broadcast_to(x[:1], (pad,) + x.shape[1:])
        return jnp.concatenate([x, ghost], axis=0)

    def _pad_member_weights(self, weights: jax.Array) -> jax.Array:
        """Zero-pad per-member rank weights from the real population to
        the padded member count (the update-side half of the ghost-member
        contract: clamped rows × zero weights contribute nothing)."""
        pad = self.members_padded - self.config.population_size
        if pad == 0:
            return weights
        return jnp.concatenate(
            [weights, jnp.zeros((pad,), weights.dtype)])

    def _eval_local(self, state: ESState, member_offs, signs, member_keys):
        """Rollout this device's members in eval_chunk-sized compiled chunks."""
        cfg = self.config
        dim = self.spec.dim
        n_chunks = self.members_local // self.eval_chunk
        if cfg.streamed:
            return self._eval_local_streamed(
                state, member_offs, signs, member_keys, n_chunks
            )
        if cfg.decomposed or cfg.low_rank:
            # shared center tree: unraveled (and, for bf16, cast) ONCE,
            # enters the member vmap as an un-batched constant — its matmuls
            # fuse across the population.  The f32 original stays around for
            # the recurrent low_rank branch, which perturbs in f32 and casts
            # per member (the standard path's theta ordering)
            center_f32 = self.spec.unravel(state.params_flat)
            shared_tree = self._member_cast(center_f32)

        def chunk_body(_, xs):
            offs_c, signs_c, keys_c = xs

            def member_eval(off, sign, key):
                if cfg.low_rank:
                    nvec = self.table.slice(off, self.noise_dim)
                    if self._carry_init is not None:
                        # recurrent: dense perturbation materialized ONCE
                        # per episode (ops/lowrank.py tree form) — noise
                        # STATE stays O(noise_dim); the rollout is the
                        # standard carry-threaded scan
                        from ..ops.lowrank import lowrank_tree_perturb

                        theta_tree = lowrank_tree_perturb(
                            self.lr_spec, center_f32, nvec,
                            state.sigma * sign,
                        )
                        rollout = self._rollout
                        params = self._member_cast(theta_tree)
                        if self._obs_norm:
                            params = (params, state.obs_stats)
                        return self._member_rollout(rollout, params, key)
                    # MLP: packed (A||B||bias) factors — dim is the LR
                    # noise_dim, and no dense noise matrix ever exists on
                    # this path
                    lrn = self.lr_spec.unpack(nvec)
                    rollout = self._rollout_lowrank
                    params = (
                        shared_tree,
                        self._member_cast(lrn),
                        self._member_cast(state.sigma * sign),
                    )
                    if self._obs_norm:
                        params = (params, state.obs_stats)
                    return self._member_rollout(rollout, params, key)
                eps = self.table.slice(off, dim)
                if cfg.decomposed:
                    rollout = self._rollout_decomposed
                    params = (
                        shared_tree,
                        self._member_cast(self.spec.unravel(eps)),
                        self._member_cast(state.sigma * sign),
                    )
                    if self._obs_norm:
                        params = (params, state.obs_stats)
                else:
                    rollout = self._rollout
                    theta = state.params_flat + state.sigma * sign * eps
                    # once-per-member cast (bf16 path): the rollout scan
                    # below runs on dtype-pure params, no per-step casts
                    params = self._member_cast(self.spec.unravel(theta))
                    if self._obs_norm:
                        # every member this generation normalizes with the
                        # SAME stats snapshot (vmap broadcasts the pack)
                        params = (params, state.obs_stats)
                return self._member_rollout(rollout, params, key)

            f, bc, st = jax.vmap(member_eval)(offs_c, signs_c, keys_c)
            return 0, (f, bc, st)

        return self._scan_chunks(chunk_body, member_offs, signs, member_keys, n_chunks)

    def _member_rollout(self, rollout, params, key):
        """One member's fitness/bc/steps, honoring episodes_per_member."""
        cfg = self.config
        if cfg.episodes_per_member > 1:
            ep_keys = jax.random.split(key, cfg.episodes_per_member)
            res = jax.vmap(rollout, in_axes=(None, 0))(params, ep_keys)
            # fitness = mean return; BC = first episode's; steps summed
            return (
                res.total_reward.mean(),
                jax.tree_util.tree_map(lambda x: x[0], res.bc),
                res.steps.sum(),
            )
        res = rollout(params, key)
        return res.total_reward, res.bc, res.steps

    def _scan_chunks(self, chunk_body, member_offs, signs, member_keys, n_chunks):
        """Dispatch the local shard through ``chunk_body`` in eval_chunk
        pieces (single-chunk: no 1-iteration scan layer) and restore the
        member-major result shapes.  Shared by the standard/decomposed vmap
        path and the streamed batched path."""
        if n_chunks == 1:
            _, (f, bc, st) = chunk_body(0, (member_offs, signs, member_keys))
        else:
            xs = (
                member_offs.reshape(n_chunks, self.eval_chunk),
                signs.reshape(n_chunks, self.eval_chunk),
                member_keys.reshape(n_chunks, self.eval_chunk, -1),
            )
            _, (f, bc, st) = jax.lax.scan(chunk_body, 0, xs)
        return (
            f.reshape(self.members_local),
            bc.reshape(self.members_local, self.bc_dim),
            st.reshape(self.members_local),
        )

    def _eval_local_streamed(self, state, member_offs, signs, member_keys, n_chunks):
        """Population-batched evaluation with the Pallas streamed forward:
        one policy call per env step for the whole chunk, every layer's ε
        DMA'd from the table — no member noise tree is ever materialized."""
        shared_tree = self.spec.unravel(state.params_flat)

        def chunk_body(_, xs):
            offs_c, signs_c, keys_c = xs
            c = state.sigma * signs_c

            def batched_apply(obs_batch):
                if self._obs_norm:
                    # stats broadcast over the population batch dim; streamed
                    # is f32-only so no dtype shim is needed
                    obs_batch = normalize_obs(
                        obs_batch, state.obs_stats, float(self.config.obs_clip)
                    )
                return self._streamed_apply(shared_tree, offs_c, c, obs_batch)

            res = self._rollout_batched(batched_apply, keys_c)
            return 0, (res.total_reward, res.bc, res.steps)

        return self._scan_chunks(chunk_body, member_offs, signs, member_keys, n_chunks)

    def _gather_global(self, fitness_local, bc_local, steps_local):
        """Device-major all_gather → identical global arrays on every device.

        Padded runs: the gathered arrays are sliced back to the REAL
        population (ghost members vanish before ranking/metrics) and
        ghost steps are masked out of the env-steps count so throughput
        numbers never include padding work."""
        cfg = self.config
        fitness = jax.lax.all_gather(fitness_local, POP_AXIS).reshape(-1)
        bc = jax.lax.all_gather(bc_local, POP_AXIS).reshape(-1, self.bc_dim)
        if self.members_padded == cfg.population_size:
            steps = jax.lax.psum(steps_local.sum(), POP_AXIS)
        else:
            d = jax.lax.axis_index(POP_AXIS)
            idx = d * self.members_local + jnp.arange(self.members_local)
            alive = idx < cfg.population_size
            steps = jax.lax.psum(
                jnp.where(alive, steps_local, 0).sum(), POP_AXIS)
            fitness = fitness[: cfg.population_size]
            bc = bc[: cfg.population_size]
        return fitness, bc, steps

    def _local_grad(self, state: ESState, weights, reduction_offs):
        """This device's pre-psum partial of the rank-weighted estimator.

        ``reduction_offs`` is per-PAIR (mirrored; folded estimator) or
        per-MEMBER (unmirrored; direct weighted sum).
        """
        cfg = self.config
        d = jax.lax.axis_index(POP_AXIS)
        weights = self._pad_member_weights(weights)
        w_local = jax.lax.dynamic_slice(
            weights, (d * self.members_local,), (self.members_local,)
        )
        if cfg.low_rank:
            # one einsum per layer over the stacked factor slices — no dense
            # E_i is ever materialized (ops/lowrank.py)
            from ..ops.gradient import fold_mirrored_weights as _fold_lr
            from ..ops.lowrank import (lowrank_tree_weighted_sum,
                                       lowrank_weighted_sum)

            row_w = _fold_lr(w_local) if cfg.mirrored else w_local
            noise_local = jax.vmap(
                lambda o: self.table.slice(o, self.noise_dim)
            )(reduction_offs)
            wsum = (lowrank_tree_weighted_sum
                    if hasattr(self.lr_spec, "treedef")
                    else lowrank_weighted_sum)
            tree = wsum(self.lr_spec, noise_local, row_w)
            grad_local = self.spec.flatten(tree) / (
                cfg.population_size * state.sigma
            )
        elif cfg.noise_kernel:
            # Pallas streamed reduction: each ε row is DMA'd once and FMA'd
            # into a VMEM accumulator — no materialized noise blocks
            from ..ops.gradient import fold_mirrored_weights as _fold
            from ..ops.pallas_noise import weighted_noise_sum

            row_w = _fold(w_local) if cfg.mirrored else w_local
            grad_local = weighted_noise_sum(
                self.table.data, reduction_offs, row_w, dim=self.spec.dim
            ) / (cfg.population_size * state.sigma)
        elif cfg.mirrored:
            # local folded partial of the estimator; scaling commutes with psum
            grad_local = es_gradient(
                self.table, reduction_offs, w_local,
                sigma=state.sigma, population_size=cfg.population_size,
                dim=self.spec.dim, chunk=cfg.grad_chunk,
            )
        else:
            grad_local = rank_weighted_noise_sum(
                self.table, reduction_offs, w_local,
                dim=self.spec.dim, chunk=cfg.grad_chunk,
            ) / (cfg.population_size * state.sigma)
        return grad_local

    def _update_from_weights(self, state: ESState, weights, reduction_offs):
        """Optax step from per-member rank weights. Identical on all devices."""
        grad_local = self._local_grad(state, weights, reduction_offs)
        grad_ascent = jax.lax.psum(grad_local, POP_AXIS)
        return self._finish_update(state, grad_ascent)

    def _finish_update(self, state: ESState, grad_ascent):
        """Weight decay + optax step + σ annealing from a replicated ascent
        direction (identical on every device by construction)."""
        cfg = self.config
        if cfg.weight_decay > 0.0:
            grad_ascent = grad_ascent - cfg.weight_decay * state.params_flat
        updates, new_opt_state = self.optimizer.update(
            -grad_ascent, state.opt_state, state.params_flat
        )
        new_params = optax.apply_updates(state.params_flat, updates)
        new_sigma = state.sigma
        if cfg.sigma_decay != 1.0:
            new_sigma = jnp.maximum(state.sigma * cfg.sigma_decay, cfg.sigma_min)
        new_obs_stats = state.obs_stats
        if self._obs_norm:
            # refresh the running stats from center-policy probe episodes —
            # deterministic and identical on every device (replicated
            # params + keys); Chan merge keeps the Welford triple O(1)
            c1, s1, q1 = self._probe_obs_moments(state)
            new_obs_stats = merge_obs_moments(state.obs_stats, c1, s1, q1)
        new_state = ESState(
            params_flat=new_params,
            opt_state=new_opt_state,
            key=state.key,
            generation=state.generation + 1,
            sigma=new_sigma,
            obs_stats=new_obs_stats,
        )
        return new_state, jnp.linalg.norm(grad_ascent)

    def _probe_moments_sum(self, base_key, n_episodes, params_flat, obs_stats):
        """Summed (count, obs_sum, obs_sumsq) over ``n_episodes`` probe
        episodes of the policy at ``params_flat`` — the ONE probe-fanout
        recipe, shared by the per-generation refresh and the init
        warm-start so their keying/batching can never diverge."""
        keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(
            jnp.arange(n_episodes)
        )
        params = self._member_cast(self.spec.unravel(params_flat))
        packed = (params, obs_stats)
        c, s, q = jax.vmap(self._obs_probe, in_axes=(None, 0))(packed, keys)
        return c.sum(), s.sum(axis=0), q.sum(axis=0)

    def _probe_obs_moments(self, state: ESState):
        """Per-generation refresh moments, keyed disjointly from
        member/center streams."""
        _, rkey = _gen_keys(state)
        base = jax.random.fold_in(rkey, 2**31 - 2)
        return self._probe_moments_sum(
            base, self.config.obs_probe_episodes,
            state.params_flat, state.obs_stats,
        )

    # ---- shard_map bodies ----

    def _generation_body(self, state: ESState):
        red_offs, member_offs, signs, member_keys = self._local_offsets_signs_keys(state)
        f_l, bc_l, st_l = self._eval_local(state, member_offs, signs, member_keys)
        fitness, bc, steps = self._gather_global(f_l, bc_l, st_l)
        # NaN-safe ranking: a failed rollout (NaN/inf fitness) is dropped and
        # survivors renormalized — same semantics as the host backend's
        # utils/fault.py::rank_weights_with_failures, but inside the program
        weights, n_valid = centered_rank_safe(fitness)
        new_state, gnorm = self._update_from_weights(state, weights, red_offs)
        metrics = {
            "fitness": fitness,
            "bc": bc,
            "steps": steps,
            "grad_norm": gnorm,
            "n_valid": n_valid,
            # post-update anomaly guard input: replicated boolean — a
            # non-finite parameter vector or update norm after the optax
            # step means ES.train must reject this generation (restore the
            # previous state) instead of training on poisoned params
            "update_finite": jnp.logical_and(
                jnp.isfinite(gnorm),
                jnp.isfinite(new_state.params_flat).all(),
            ),
        }
        return new_state, metrics

    def _evaluate_body(self, state: ESState):
        _, member_offs, signs, member_keys = self._local_offsets_signs_keys(state)
        f_l, bc_l, st_l = self._eval_local(state, member_offs, signs, member_keys)
        fitness, bc, steps = self._gather_global(f_l, bc_l, st_l)
        return EvalResult(fitness=fitness, bc=bc, steps=steps)

    def _apply_weights_body(self, state: ESState, weights):
        red_offs, _, _, _ = self._local_offsets_signs_keys(state)
        new_state, gnorm = self._update_from_weights(state, weights, red_offs)
        return new_state, gnorm

    # ---- public API ----

    def init_state(self, params_flat: jax.Array, key: jax.Array) -> ESState:
        import chex

        chex.assert_shape(params_flat, (self.spec.dim,))
        chex.assert_tree_all_finite(params_flat)
        obs_stats = None
        if self._obs_norm:
            # count=1, mean=0, m2=1 → var 1: the first generation
            # normalizes as identity-ish and real moments take over as the
            # probe count grows
            obs_dim = int(self.env.obs_dim)
            obs_stats = (
                jnp.float32(1.0),
                jnp.zeros((obs_dim,), jnp.float32),
                jnp.ones((obs_dim,), jnp.float32),
            )
            warm = self.config.obs_warmup_episodes
            if warm > 0:
                # warm-start: init-policy probe episodes folded in BEFORE
                # generation 0, keyed disjointly from every training
                # stream (member/center/per-gen-probe use fold_in of the
                # per-generation base; this folds the RAW state key).
                # init_state runs host-side, so the f64 merge is free —
                # and warmup is exactly the many-episodes-at-once case
                # the in-program f32 merge is documented unsafe for.
                import numpy as np

                base = jax.random.fold_in(key, 2**31 - 3)
                c, s, q = self._probe_moments_sum(
                    base, warm, params_flat, obs_stats
                )
                obs_stats = merge_obs_moments_np(
                    obs_stats, float(c), np.asarray(s), np.asarray(q)
                )
        return ESState(
            params_flat=params_flat,
            opt_state=self.optimizer.init(params_flat),
            key=key,
            generation=jnp.int32(0),
            sigma=jnp.float32(self.config.sigma),
            obs_stats=obs_stats,
        )

    def compile(self, state: ESState) -> float:
        """AOT-compile the fused generation program; returns seconds spent.

        Called once before the timed loop so env-steps/sec — the primary
        metric — never includes XLA trace+compile time.
        """
        import time as _time

        t0 = _time.perf_counter()
        compiled = self._generation_step.lower(state).compile()
        dt = _time.perf_counter() - t0
        # ledger entry + recompiles counter + per-program gauges + ring
        # event in one call; `compiled` contributes XLA's own FLOPs/bytes/
        # peak-memory estimates where this jax version exposes them
        # (obs/profile/ledger.py)
        self.telemetry.compile_event("generation_step", dt,
                                     compiled=compiled, first_call=True)
        return dt

    def compile_split(self, state: ESState) -> float:
        """AOT-compile the split-path programs (evaluate, apply_weights,
        center eval) used by the novelty family; returns seconds spent."""
        import time as _time

        total = 0.0
        dummy_w = jnp.zeros((self.config.population_size,), jnp.float32)
        for program, lowered in (
            ("evaluate", lambda: self._evaluate.lower(state)),
            ("apply_weights", lambda: self._apply_weights.lower(state,
                                                               dummy_w)),
            ("center_eval", lambda: self._center_eval.lower(state)),
        ):
            t0 = _time.perf_counter()
            compiled = lowered().compile()
            dt = _time.perf_counter() - t0
            # per-program ledger entries: the split path's three programs
            # have very different costs, and the ledger is what tells
            # them apart (one blended "split_path" entry could not)
            self.telemetry.compile_event(program, dt, compiled=compiled,
                                         first_call=True)
            total += dt
        return total

    def generation_step(self, state: ESState):
        """Fused ES generation: returns (new_state, metrics dict)."""
        return self._generation_step(state)

    def evaluate(self, state: ESState) -> EvalResult:
        """Population evaluation only (novelty family / center evaluation)."""
        return self._evaluate(state)

    def apply_weights(self, state: ESState, weights: jax.Array):
        """Update from host-computed per-member weights (novelty family)."""
        return self._apply_weights(state, weights)

    # ---- importance-weighted sample reuse (algo/iwes.py) ----

    def _require_dense_noise(self, what: str):
        if self.config.low_rank:
            raise ValueError(
                f"{what} needs the dense (dim,) noise representation. "
                "low_rank packs rank-r factors instead (ops/lowrank.py), "
                "and IW reuse is not merely unimplemented there — it is "
                "ill-posed: the reused perturbation seen from the drifted "
                "center, dense(v) + (c_old - c_new)/sigma, generally lies "
                "outside the rank-r image, so no factor-space importance "
                "ratio exists (the induced distribution on dense "
                "perturbations is singular; ROADMAP item 7)"
            )

    def noise_stats(self, offsets: jax.Array, d_vec: jax.Array):
        """(ε·d, |ε|²) for every table row in ``offsets`` — the per-sample
        statistics the importance ratio λ needs (algo/iwes.py).  Sharded:
        each device computes its contiguous block, results all_gather'd."""
        self._require_dense_noise("noise_stats")
        if not hasattr(self, "_noise_stats_progs"):
            self._noise_stats_progs = {}
        cache_n = int(offsets.shape[0])
        if cache_n not in self._noise_stats_progs:
            n = cache_n
            k_local = n // self.n_devices
            if k_local * self.n_devices != n:
                raise ValueError(
                    f"offsets ({n}) must divide evenly over {self.n_devices} "
                    "devices"
                )
            chunk = _choose_eval_chunk(self.config.grad_chunk, k_local)

            def body(offs, d_vec):
                dev = jax.lax.axis_index(POP_AXIS)
                o_local = jax.lax.dynamic_slice(offs, (dev * k_local,), (k_local,))

                def chunk_stats(_, o_c):
                    eps = jax.vmap(lambda o: self.table.slice(o, self.spec.dim))(o_c)
                    return 0, (eps @ d_vec, jnp.sum(eps * eps, axis=-1))

                if k_local == chunk:
                    _, (dots, norms) = chunk_stats(0, o_local)
                else:
                    _, (dots, norms) = jax.lax.scan(
                        chunk_stats, 0, o_local.reshape(-1, chunk)
                    )
                    dots = dots.reshape(k_local)
                    norms = norms.reshape(k_local)
                return (
                    jax.lax.all_gather(dots, POP_AXIS).reshape(-1),
                    jax.lax.all_gather(norms, POP_AXIS).reshape(-1),
                )

            self._noise_stats_progs[cache_n] = jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=(P(), P()),
                    out_specs=(P(), P()), check_vma=False,
                )
            )
        return self._noise_stats_progs[cache_n](offsets, d_vec)

    def apply_weights_reuse(
        self, state: ESState, weights: jax.Array, old_offsets: jax.Array,
        old_w: jax.Array, d_stack: jax.Array, coeff_d,
    ):
        """Update from fresh rank weights PLUS reused-sample terms.

        Supports a multi-generation reuse window: ``old_offsets``/``old_w``
        are the CONCATENATION over reused generations (per old PAIR when
        mirrored, per old member otherwise), ``d_stack`` is (n_gens, dim)
        of per-generation drift vectors and ``coeff_d`` their (n_gens,)
        coefficients.  The combined-estimator scaling contract
        (algo/iwes.py): ``weights`` are pre-scaled so the engine's internal
        1/(population·σ) yields 1/(n_total·σ); ``old_w`` and ``coeff_d``
        arrive FULLY pre-scaled, so the reuse terms are added raw:
        ∇̂ += Σ old_w·ε_old + coeff_d @ d_stack.
        """
        self._require_dense_noise("apply_weights_reuse")
        d_stack = jnp.atleast_2d(d_stack)
        coeff_d = jnp.atleast_1d(jnp.asarray(coeff_d, jnp.float32))
        if not hasattr(self, "_apply_weights_reuse_progs"):
            self._apply_weights_reuse_progs = {}
        cache_key = (int(old_offsets.shape[0]), int(d_stack.shape[0]))
        if cache_key not in self._apply_weights_reuse_progs:
            n_old = cache_key[0]
            k_local = n_old // self.n_devices
            if k_local * self.n_devices != n_old:
                raise ValueError(
                    f"old_offsets ({n_old}) must divide evenly over "
                    f"{self.n_devices} devices"
                )

            def body(state, weights, old_offs, old_w, d_st, cd):
                red_offs, _, _, _ = self._local_offsets_signs_keys(state)
                grad_local = self._local_grad(state, weights, red_offs)
                dev = jax.lax.axis_index(POP_AXIS)
                o_local = jax.lax.dynamic_slice(
                    old_offs, (dev * k_local,), (k_local,)
                )
                w_local = jax.lax.dynamic_slice(
                    old_w, (dev * k_local,), (k_local,)
                )
                grad_local = grad_local + rank_weighted_noise_sum(
                    self.table, o_local, w_local,
                    dim=self.spec.dim, chunk=self.config.grad_chunk,
                )
                grad_ascent = jax.lax.psum(grad_local, POP_AXIS)
                grad_ascent = grad_ascent + cd @ d_st
                return self._finish_update(state, grad_ascent)

            self._apply_weights_reuse_progs[cache_key] = jax.jit(
                shard_map(
                    body, mesh=self.mesh,
                    in_specs=(P(), P(), P(), P(), P(), P()),
                    out_specs=(P(), P()),
                    check_vma=False,
                )
            )
        return self._apply_weights_reuse_progs[cache_key](
            state, weights, old_offsets, old_w, d_stack, coeff_d,
        )

    def evaluate_center(self, state: ESState):
        """One episode with the unperturbed center params → RolloutResult."""
        return self._center_eval(state)

    def member_params(self, state: ESState, member_index: int) -> jax.Array:
        """Reconstruct one member's flat params from the noise table (host
        convenience — e.g. to snapshot the best member, reference's
        ``best_policy``)."""
        okey, _ = _gen_keys(state)
        if self.config.mirrored:
            all_pair_offsets = sample_pair_offsets(
                okey, self.config.population_size // 2, self.table.size, self.noise_dim
            )
            off = all_pair_offsets[member_index // 2]
            sign = 1.0 if member_index % 2 == 0 else -1.0
        else:
            all_offsets = sample_pair_offsets(
                okey, self.config.population_size, self.table.size, self.noise_dim
            )
            off = all_offsets[member_index]
            sign = 1.0
        if self.config.low_rank:
            from ..ops.lowrank import lowrank_noise_tree, lowrank_tree_noise

            mk = (lowrank_tree_noise if hasattr(self.lr_spec, "treedef")
                  else lowrank_noise_tree)
            dense = mk(self.lr_spec, self.table.slice(off, self.noise_dim))
            return state.params_flat + state.sigma * sign * self.spec.flatten(dense)
        eps = self.table.slice(off, self.spec.dim)
        return state.params_flat + state.sigma * sign * eps
