"""Process-based host workers — the reference's n_proc semantics, GIL-free.

The reference fans rollouts over OS processes (torch.distributed / MPI,
SURVEY.md §2 item 7).  HostEngine's default thread workers are enough when
gym/torch release the GIL, but pure-Python rollout code serializes; this
pool forks real processes instead:

- fork inherits the policy/agent FACTORIES and the shared noise table
  (copy-on-write — the table is never shipped over a pipe);
- each worker lazily builds its own scratch policy + agent after fork
  (no pickling of user objects, no shared stateful envs);
- per generation each worker receives only (params_flat, sigma, offsets)
  once and evaluates its member slice; results return as
  (indices, fitness, bc, steps) arrays;
- a worker that dies mid-generation marks its whole slice NaN — the
  straggler-drop path (utils/fault.py) renormalizes the update, exactly the
  recovery SURVEY.md §5 prescribes (the reference hangs forever here).
"""

from __future__ import annotations

import multiprocessing as mp
import os
from typing import Any, Callable

import numpy as np


def _worker_main(
    conn,
    policy_factory: Callable[[], Any],
    agent_factory: Callable[[], Any],
    worker_id: int,
    n_proc: int,
    population_size: int,
    dim: int,
    table,  # numpy array, shared via fork COW
    master_state,  # master policy state_dict (fork-inherited) — syncs BUFFERS
    mirrored: bool = True,
):
    """Worker loop: build policy/agent once, evaluate member slices forever."""
    import torch

    torch.set_num_threads(1)  # workers parallelize across processes, not BLAS
    policy = policy_factory()
    # vector_to_parameters only writes parameters; buffers (frozen VBN stats,
    # running means) must come from the master, same as thread scratch policies
    policy.load_state_dict(master_state)
    agent = agent_factory()

    def load(flat):
        with torch.no_grad():
            torch.nn.utils.vector_to_parameters(
                torch.from_numpy(np.ascontiguousarray(flat)).clone(),
                policy.parameters(),
            )

    # reuse the duck-typed rollout parsing + the single noise-indexing rule
    from .engine import HostEngine, member_sign_offset

    while True:
        msg = conn.recv()
        if msg is None:
            return
        seq, params_flat, sigma, offsets = msg
        indices = list(range(worker_id, population_size, n_proc))
        fitness = np.full(len(indices), np.nan, np.float32)
        bcs: list[np.ndarray] = []
        steps = 0
        for j, i in enumerate(indices):
            sign, off = member_sign_offset(offsets, i, mirrored)
            theta = params_flat + sigma * sign * table[off : off + dim]
            load(theta)
            try:
                res = HostEngine._call_rollout(agent, policy)
            except Exception:  # noqa: BLE001 — NaN marks the member failed
                bcs.append(np.zeros(0, np.float32))
                continue
            fitness[j] = res.total_reward
            bcs.append(res.bc)
            steps += res.steps
        bc_dim = max((b.shape[0] for b in bcs), default=0)
        bc = np.zeros((len(indices), bc_dim), np.float32)
        for j, b in enumerate(bcs):
            if b.shape[0]:
                bc[j] = b
        conn.send((seq, np.asarray(indices, np.int64), fitness, bc, steps))


class ProcessPool:
    """Persistent fork-based worker team for HostEngine."""

    def __init__(
        self,
        policy_factory,
        agent_factory,
        n_proc: int,
        population_size: int,
        dim: int,
        table: np.ndarray,
        master_state=None,
        mirrored: bool = True,
    ):
        if os.name != "posix":
            raise RuntimeError("process workers need fork (posix)")
        ctx = mp.get_context("fork")
        self.n_proc = int(n_proc)
        self.population_size = population_size
        self._seq = 0
        if master_state is None:
            master_state = policy_factory().state_dict()
        self._procs = []
        self._conns = []
        for w in range(self.n_proc):
            parent, child = ctx.Pipe()
            p = ctx.Process(
                target=_worker_main,
                args=(child, policy_factory, agent_factory, w, self.n_proc,
                      population_size, dim, table, master_state, mirrored),
                daemon=True,
            )
            p.start()
            child.close()
            self._procs.append(p)
            self._conns.append(parent)

    def evaluate(self, params_flat: np.ndarray, sigma: float, offsets: np.ndarray,
                 timeout_s: float = 600.0):
        """Fan one generation out; returns (fitness, bc, steps) with dead
        workers' slices left NaN (straggler-drop handles them upstream)."""
        self._seq += 1
        seq = self._seq
        msg = (seq, np.asarray(params_flat, np.float32), float(sigma),
               np.asarray(offsets))
        for c in self._conns:
            try:
                c.send(msg)
            except (BrokenPipeError, OSError):
                pass  # dead worker: its slice stays NaN

        fitness = np.full(self.population_size, np.nan, np.float32)
        parts = []
        for w, c in enumerate(self._conns):
            if not self._procs[w].is_alive() and not c.poll(0):
                continue
            # drain: a straggler from a PREVIOUS generation may have queued a
            # stale result — sequence tags keep generations from mixing
            while c.poll(timeout_s):
                try:
                    got = c.recv()
                except (EOFError, OSError):
                    break
                if got[0] == seq:
                    parts.append(got[1:])
                    break
                # got[0] < seq: stale straggler result — discard, keep polling
        bc_dim = max((p[2].shape[1] for p in parts), default=0)
        bc = np.zeros((self.population_size, bc_dim), np.float32)
        steps = 0
        for indices, f, b, st in parts:
            fitness[indices] = f
            if b.shape[1]:
                bc[indices] = b
            steps += st
        return fitness, bc, steps

    def close(self) -> None:
        for c in self._conns:
            try:
                c.send(None)
                c.close()
            except (BrokenPipeError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
