"""Process-based host workers — the reference's n_proc semantics, GIL-free.

The reference fans rollouts over OS processes (torch.distributed / MPI,
SURVEY.md §2 item 7).  HostEngine's default thread workers are enough when
gym/torch release the GIL, but pure-Python rollout code serializes; this
pool forks real processes instead:

- fork inherits the policy/agent FACTORIES and the shared noise table
  (copy-on-write — the table is never shipped over a pipe);
- each worker lazily builds its own scratch policy + agent after fork
  (no pickling of user objects, no shared stateful envs);
- per generation each worker receives only (params_flat, sigma, offsets)
  once and evaluates its member slice; results return as
  (indices, fitness, bc, steps) arrays.

Failure model (docs/resilience.md) — worker death is expected, not fatal:

- detection: results are collected in SHORT poll slices against one
  generation-level deadline (``timeout_s``), and a worker that is gone
  with nothing buffered is dropped immediately — a corpse never makes the
  pool sit out the full timeout on a silent pipe;
- same-generation retry: a dead worker's un-evaluated slice is
  redistributed over the surviving workers before the generation
  returns, so a single worker death costs latency, not population
  participation (the noise indexing is member-keyed, so any worker can
  evaluate any member);
- respawn: dead workers are replaced at the next generation boundary
  (:meth:`ProcessPool.respawn_dead`) with fresh forks carrying the same
  factories/master buffers;
- last resort: slices that still have no result by the deadline (alive
  stragglers, retry failures) stay NaN — the straggler-drop path
  (utils/fault.py) renormalizes the update, exactly the recovery
  SURVEY.md §5 prescribes (the reference hangs forever here).
"""

from __future__ import annotations

import multiprocessing as mp
import multiprocessing.connection as mpc
import os
import time
from typing import Any, Callable

import numpy as np

from ..obs.spans import NULL_TELEMETRY

# poll slice for result collection: long enough to stay off the CPU,
# short enough that a worker dying mid-generation is noticed in ~this
# time rather than after the full generation deadline
POLL_SLICE_S = 0.1

# idle-loop poll slice inside the worker: only paid while the worker has
# nothing to do, and what makes a dead parent's EOF observable
WORKER_POLL_S = 1.0


def _worker_main(
    conn,
    policy_factory: Callable[[], Any],
    agent_factory: Callable[[], Any],
    worker_id: int,
    n_proc: int,
    population_size: int,
    dim: int,
    table,  # numpy array, shared via fork COW
    master_state,  # master policy state_dict (fork-inherited) — syncs BUFFERS
    mirrored: bool = True,
):
    """Worker loop: build policy/agent once, evaluate member slices forever.

    Messages are ``(seq, generation, params_flat, sigma, offsets, indices)``;
    ``indices=None`` means the worker's own round-robin slice, an explicit
    array is a retry assignment for another (dead) worker's members.
    """
    import torch

    torch.set_num_threads(1)  # workers parallelize across processes, not BLAS
    policy = policy_factory()
    # vector_to_parameters only writes parameters; buffers (frozen VBN stats,
    # running means) must come from the master, same as thread scratch policies
    policy.load_state_dict(master_state)
    agent = agent_factory()

    def load(flat):
        with torch.no_grad():
            torch.nn.utils.vector_to_parameters(
                torch.from_numpy(np.ascontiguousarray(flat)).clone(),
                policy.parameters(),
            )

    # reuse the duck-typed rollout parsing + the single noise-indexing rule
    from ..resilience.chaos import member_fault
    from .engine import HostEngine, member_sign_offset

    while True:
        # bounded idle wait before the blocking recv (esguard R11): a
        # parent that died without sending the stop sentinel leaves the
        # pipe EOF-readable, which poll surfaces and recv turns into a
        # clean exit instead of an unbounded sleep on a dead fd
        if not conn.poll(WORKER_POLL_S):
            continue
        try:
            msg = conn.recv()
        except EOFError:
            return  # parent end closed: nothing more will ever come
        if msg is None:
            return
        seq, generation, params_flat, sigma, offsets, indices = msg
        if indices is None:
            indices = list(range(worker_id, population_size, n_proc))
        else:
            indices = [int(i) for i in indices]
        fitness = np.full(len(indices), np.nan, np.float32)
        bcs: list[np.ndarray] = []
        steps = 0
        t0 = time.perf_counter()
        for j, i in enumerate(indices):
            sign, off = member_sign_offset(offsets, i, mirrored)
            theta = params_flat + sigma * sign * table[off : off + dim]
            load(theta)
            try:
                member_fault(generation, i)  # deterministic chaos injection
                res = HostEngine._call_rollout(agent, policy)
            except Exception:  # noqa: BLE001 — NaN marks the member failed
                bcs.append(np.zeros(0, np.float32))
                continue
            fitness[j] = res.total_reward
            bcs.append(res.bc)
            steps += res.steps
        bc_dim = max((b.shape[0] for b in bcs), default=0)
        bc = np.zeros((len(indices), bc_dim), np.float32)
        for j, b in enumerate(bcs):
            if b.shape[0]:
                bc[j] = b
        conn.send((seq, np.asarray(indices, np.int64), fitness, bc, steps,
                   time.perf_counter() - t0))


class ProcessPool:
    """Persistent fork-based worker team for HostEngine."""

    # span/counter hub; HostEngine points this at its own telemetry so
    # respawn/retry counters land in the run's registry
    telemetry = NULL_TELEMETRY

    def __init__(
        self,
        policy_factory,
        agent_factory,
        n_proc: int,
        population_size: int,
        dim: int,
        table: np.ndarray,
        master_state=None,
        mirrored: bool = True,
    ):
        if os.name != "posix":
            raise RuntimeError("process workers need fork (posix)")
        self._ctx = mp.get_context("fork")
        self.n_proc = int(n_proc)
        self.population_size = population_size
        self.dim = dim
        self._seq = 0
        if master_state is None:
            master_state = policy_factory().state_dict()
        self._spawn_args = (policy_factory, agent_factory, self.n_proc,
                           population_size, dim, table, master_state,
                           mirrored)
        self._procs: list[Any] = []
        self._conns: list[Any] = []
        self._retired: list[Any] = []  # replaced dead workers, joined at close
        self._eof: set[int] = set()  # workers whose pipe EOF'd (poll skips)
        for w in range(self.n_proc):
            self._procs.append(None)
            self._conns.append(None)
            self._spawn(w)

    def _spawn(self, w: int) -> None:
        (policy_factory, agent_factory, n_proc, population_size, dim, table,
         master_state, mirrored) = self._spawn_args
        parent, child = self._ctx.Pipe()
        p = self._ctx.Process(
            target=_worker_main,
            args=(child, policy_factory, agent_factory, w, n_proc,
                  population_size, dim, table, master_state, mirrored),
            daemon=True,
        )
        p.start()
        child.close()
        self._procs[w] = p
        self._conns[w] = parent
        self._eof.discard(w)

    @property
    def worker_pids(self) -> list[int]:
        return [p.pid for p in self._procs]

    def respawn_dead(self) -> int:
        """Replace dead workers with fresh forks (generation-boundary call).
        The dead worker's pipe is closed (any buffered stale result is
        dropped with it) and the corpse parked for ``close()`` to join."""
        n = 0
        for w, p in enumerate(self._procs):
            if p.is_alive():
                continue
            try:
                self._conns[w].close()
            except OSError:
                self.telemetry.event("respawn_conn_close_failed", worker=w)
            self._retired.append(p)
            self._spawn(w)
            n += 1
            self.telemetry.counters.inc("workers_respawned")
            self.telemetry.event("worker_respawned", worker=w,
                                 pid=self._procs[w].pid)
        return n

    # ------------------------------------------------------------ evaluate

    def _send(self, w: int, msg) -> bool:
        try:
            self._conns[w].send(msg)
            return True
        except (BrokenPipeError, OSError):
            # dead worker: its slice is handled by the retry/NaN-drop path
            self.telemetry.counters.inc("worker_send_failures")
            return False

    def _collect(self, seq: int, pending: dict[int, Any], deadline: float,
                 parts: list) -> None:
        """Drain results for ``seq`` from ``pending`` (worker id → conn)
        until all answered, each dead-with-empty-pipe worker is dropped,
        or the shared generation deadline passes.  Stale results from
        earlier sequences (late stragglers) are discarded by tag."""
        conn_to_w = {id(c): w for w, c in pending.items()}
        while pending:
            left = deadline - time.monotonic()
            if left <= 0:
                return  # generation deadline: leftovers stay NaN/retryable
            ready = mpc.wait(list(pending.values()),
                             timeout=min(left, POLL_SLICE_S))
            if not ready:
                # nothing buffered: drop workers that are gone — a corpse
                # with an empty pipe will never answer, and waiting the
                # full deadline on it is the exact hang this loop replaces
                for w in [w for w, c in pending.items()
                          if not self._procs[w].is_alive() and not c.poll(0)]:
                    del pending[w]
                continue
            for c in ready:
                w = conn_to_w[id(c)]
                try:
                    got = c.recv()
                except (EOFError, OSError):
                    del pending[w]  # pipe closed under us: worker died
                    continue
                if got[0] == seq:
                    parts.append(got[1:])
                    del pending[w]
                # got[0] < seq: stale straggler result — discard, keep going

    def evaluate(self, params_flat: np.ndarray, sigma: float,
                 offsets: np.ndarray, timeout_s: float = 600.0,
                 generation: int = 0):
        """Fan one generation out; returns (fitness, bc, steps).

        ``timeout_s`` bounds the whole GENERATION (one shared deadline),
        not each worker's pipe.  Slices owned by workers that died are
        retried once on the survivors within the same generation; only
        what is still unanswered at the deadline (or after the retry)
        stays NaN for the straggler-drop path upstream.
        """
        self._seq += 1
        seq = self._seq
        deadline = time.monotonic() + timeout_s
        msg = (seq, int(generation), np.asarray(params_flat, np.float32),
               float(sigma), np.asarray(offsets), None)
        pending = {w: self._conns[w] for w in range(self.n_proc)
                   if self._send(w, msg)}

        parts: list = []
        self._collect(seq, pending, deadline, parts)

        # same-generation retry: members owned by DEAD workers never got
        # evaluated — survivors can cover them (member-keyed noise indexing
        # means any worker computes the identical theta).  Alive stragglers
        # are NOT retried: their results may still arrive, and duplicating
        # them would only double the load that made them late.
        covered: set[int] = set()
        for indices, _f, _b, _s, _t in parts:
            covered.update(int(i) for i in indices)
        missing = [i for i in range(self.population_size) if i not in covered
                   and not self._procs[i % self.n_proc].is_alive()]
        alive = [w for w in range(self.n_proc) if self._procs[w].is_alive()]
        if missing and alive and deadline - time.monotonic() > 0:
            self.telemetry.counters.inc("slice_retries")
            self.telemetry.counters.inc("members_retried", len(missing))
            self.telemetry.event("slice_retry", members=len(missing),
                                 survivors=len(alive), gen=int(generation))
            self._seq += 1
            rseq = self._seq
            retry_pending: dict[int, Any] = {}
            for k, w in enumerate(alive):
                chunk = missing[k::len(alive)]
                if chunk and self._send(w, (rseq, int(generation),
                                            msg[2], msg[3], msg[4],
                                            np.asarray(chunk, np.int64))):
                    retry_pending[w] = self._conns[w]
            self._collect(rseq, retry_pending, deadline, parts)

        fitness = np.full(self.population_size, np.nan, np.float32)
        bc_dim = max((p[2].shape[1] for p in parts), default=0)
        bc = np.zeros((self.population_size, bc_dim), np.float32)
        steps = 0
        for indices, f, b, st, _t in parts:
            fitness[indices] = f
            if b.shape[1]:
                bc[indices] = b
            steps += st
        return fitness, bc, steps

    # ------------------------------------------------- async (scheduler)

    def dispatch(self, worker: int, params_flat: np.ndarray, sigma: float,
                 offsets: np.ndarray, generation: int,
                 indices=None) -> int | None:
        """Async API (algo/scheduler.py): send ONE slice message to
        ``worker`` and return its sequence tag, or None when the pipe is
        dead (the caller accounts the slice as lost).  ``indices=None``
        means the worker's own round-robin slice."""
        self._seq += 1
        msg = (self._seq, int(generation),
               np.asarray(params_flat, np.float32), float(sigma),
               np.asarray(offsets),
               None if indices is None else np.asarray(indices, np.int64))
        return self._seq if self._send(worker, msg) else None

    def poll(self, timeout_s: float) -> list[tuple]:
        """Async API: one bounded wait, then drain every buffered reply —
        (seq, indices, fitness, bc, steps, eval_s) tuples for EVERY
        sequence tag, late straggler replies included.  Staleness policy
        belongs to the scheduler; unlike the synchronous ``_collect``,
        nothing is discarded here."""
        live = {id(c): w for w, c in enumerate(self._conns)
                if c is not None and not c.closed and w not in self._eof}
        if not live:
            time.sleep(min(timeout_s, POLL_SLICE_S))
            return []
        out: list[tuple] = []
        ready = mpc.wait([self._conns[w] for w in live.values()],
                         timeout=timeout_s)
        for c in ready:
            w = live[id(c)]
            try:
                out.append(c.recv())
            except (EOFError, OSError):
                # dead pipe: exclude from future polls until respawned,
                # or an EOF-readable corpse would turn poll into a spin
                self._eof.add(w)
        return out

    def worker_alive(self, w: int) -> bool:
        return self._procs[w].is_alive()

    def conn_has_data(self, w: int) -> bool:
        """A buffered reply survives its writer — drainable by poll."""
        try:
            return w not in self._eof and self._conns[w].poll(0)
        except (OSError, EOFError):
            return False

    # --------------------------------------------------------------- close

    def close(self) -> None:
        for c in self._conns:
            try:
                if not c.closed:
                    c.send(None)
            except (BrokenPipeError, OSError):
                pass  # worker already dead: nothing to tell — the close
                # below still reclaims the parent end's fd
            try:
                if not c.closed:
                    c.close()
            except OSError:
                pass
        # join everything ever spawned — including workers replaced by
        # respawn_dead — so long chaos runs leak neither zombies nor fds
        for p in (*self._procs, *self._retired):
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
        self._retired.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
