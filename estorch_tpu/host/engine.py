"""HostEngine — the reference-parity execution backend.

The reference's entire runtime is this path: per-member Python loop calling
a user-supplied ``Agent.rollout(policy)`` (policy = a ``torch.nn.Module``),
fitness gathered, master applies a torch-optimizer step (SURVEY.md §3.2-3.3).
estorch_tpu keeps that contract alive so reference users' Agents, torch
policies, and torch optimizers run unchanged:

    es = ES(TorchPolicy, GymAgent, torch.optim.Adam, ...)
    es.train(n_steps, n_proc=8)

Differences from the reference runtime (deliberate upgrades):
- ``n_proc`` maps to a thread pool with per-worker scratch policy + agent
  instances instead of ``torch.distributed`` processes — no MPI, no gloo,
  no parameter broadcast; gym/mujoco/torch release the GIL in their C cores.
- noise comes from the same shared-noise-table design as the device path
  (offsets per antithetic pair, regenerated — never stored per member), so
  memory is O(table), not O(population×dim).
- the update is the identical folded mirrored-pair estimator
  (ops/gradient.py math, NumPy edition).

This backend exists for PARITY and portability; the TPU engine
(parallel/engine.py) is the performance path.  Both implement the same
engine interface, so ES / NS_ES / NSR_ES / NSRA_ES run on either.
"""

from __future__ import annotations

import dataclasses
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, NamedTuple

import numpy as np

from ..obs.spans import NULL_TELEMETRY
from ..ops.ranks import centered_rank_np


class HostState(NamedTuple):
    """Host twin of parallel.engine.ESState (numpy-backed)."""

    params_flat: np.ndarray
    opt_state: Any  # opaque: the torch optimizer mutates in place; None otherwise
    key: int
    generation: int
    sigma: float | None = None  # current perturbation scale (annealable,
    # per center); None = pre-sigma-field state, engine falls back to init σ


class HostEvalResult(NamedTuple):
    fitness: np.ndarray
    bc: np.ndarray
    steps: int


class HostRolloutResult(NamedTuple):
    total_reward: float
    bc: np.ndarray
    steps: int


def member_sign_offset(offs: np.ndarray, i: int, mirrored: bool) -> tuple[float, int]:
    """Member i's perturbation sign and noise-table offset.  THE single
    definition of the host noise indexing — thread workers (HostEngine),
    fork workers (procpool), and member_params reconstruction must all
    agree or fitness attribution silently corrupts."""
    if mirrored:
        return (1.0 if i % 2 == 0 else -1.0), int(offs[i // 2])
    return 1.0, int(offs[i])


class HostEngine:
    """Same interface as ESEngine, executed by host workers.

    ``policy_factory()`` must return a fresh policy instance; ``agent_factory()``
    a fresh agent whose ``rollout(policy)`` returns ``reward`` or
    ``(reward, bc)`` — the reference's duck-typed contract (SURVEY.md
    Appendix A).
    """

    # span telemetry hub; ES replaces this with its own (obs/spans.py).
    # Class-level null default so instrumented paths never branch on None.
    telemetry = NULL_TELEMETRY

    def __init__(
        self,
        policy_factory: Callable[[], Any],
        agent_factory: Callable[[], Any],
        optimizer_ctor,  # torch.optim class
        optimizer_kwargs: dict,
        population_size: int,
        sigma: float,
        table_size: int,
        seed: int,
        n_proc: int = 1,
        device: str = "cpu",
        prototype_agent: Any | None = None,
        weight_decay: float = 0.0,
        worker_mode: str = "thread",
        proc_timeout_s: float = 600.0,
        sigma_decay: float = 1.0,
        sigma_min: float = 0.0,
        mirrored: bool = True,
    ):
        import torch

        self.torch = torch
        self.mirrored = bool(mirrored)
        if mirrored and population_size % 2 != 0:
            raise ValueError(
                f"population_size must be even (mirrored sampling), got {population_size}"
            )
        self.population_size = population_size
        self.n_pairs = population_size // 2
        self.sigma = float(sigma)
        self.sigma_decay = float(sigma_decay)
        self.sigma_min = float(sigma_min)
        self.weight_decay = float(weight_decay)
        self.seed = int(seed)
        self.device = device
        self.policy_factory = policy_factory
        self.agent_factory = agent_factory

        self.master = policy_factory().to(device)
        self.dim = int(
            sum(p.numel() for p in self.master.parameters())
        )
        if self.dim > table_size:
            raise ValueError(
                f"parameter dim {self.dim} exceeds noise table size {table_size}"
            )
        # float32 standard-normal table; same role as ops/noise.py, host edition
        self.table = (
            np.random.default_rng(seed).standard_normal(table_size, dtype=np.float32)
        )
        self.table_size = table_size
        self._optimizer_ctor = optimizer_ctor
        self._optimizer_kwargs = dict(optimizer_kwargs)
        self.optimizer = optimizer_ctor(self.master.parameters(), **optimizer_kwargs)

        if worker_mode not in ("thread", "process"):
            raise ValueError(
                f"worker_mode must be 'thread' or 'process', got {worker_mode!r}"
            )
        self.worker_mode = worker_mode
        # per-generation straggler budget PER WORKER in process mode; size to
        # population/n_proc × slowest-rollout (slices that exceed it are
        # NaN-dropped). Mutable attribute: es.engine.proc_timeout_s = ...
        self.proc_timeout_s = float(proc_timeout_s)
        self._prototype_agent = prototype_agent
        self._workers: list[tuple[Any, Any]] = []  # (scratch policy, agent)
        self._pool: ThreadPoolExecutor | None = None
        self._proc_pool = None  # lazily built ProcessPool (process mode)
        self.set_n_proc(n_proc)

    # ---------------------------------------------------------------- setup

    def _new_scratch_policy(self):
        p = self.policy_factory().to(self.device)
        # sync buffers too (e.g. TorchVirtualBatchNorm frozen stats):
        # parameter loads later only overwrite parameters
        p.load_state_dict(self.master.state_dict())
        return p

    def set_n_proc(self, n_proc: int) -> None:
        """Grow the worker set (scratch policy + agent per worker) and keep a
        persistent thread pool — no per-generation thread spawn/join.

        Process mode builds only worker 0 (used by evaluate_center); the
        fork pool owns its own per-process policies/agents."""
        n_proc = max(1, int(n_proc))
        want_local = 1 if self.worker_mode == "process" else n_proc
        while len(self._workers) < want_local:
            agent = (
                self._prototype_agent
                if not self._workers and self._prototype_agent is not None
                else self.agent_factory()
            )
            self._workers.append((self._new_scratch_policy(), agent))
        if self.worker_mode == "thread" and (
            self._pool is None or n_proc != getattr(self, "n_proc", None)
        ):
            if self._pool is not None:
                self._pool.shutdown(wait=False)
            self._pool = ThreadPoolExecutor(max_workers=n_proc)
        self.n_proc = n_proc

    def freeze_vbn(self, reference_batch) -> None:
        """(Re-)freeze TorchVirtualBatchNorm stats in master from a reference
        batch and propagate the buffers to every existing scratch policy
        (future workers inherit via _new_scratch_policy's state_dict copy)."""
        import torch

        from ..models.vbn_torch import TorchVirtualBatchNorm

        # clear any previously-frozen stats so this batch actually takes
        # (forward only lazy-initializes on the FIRST batched pass)
        for m in self.master.modules():
            if isinstance(m, TorchVirtualBatchNorm):
                m.initialized.fill_(False)
        with torch.no_grad():
            self.master(torch.as_tensor(np.asarray(reference_batch),
                                        dtype=torch.float32))
        for policy, _ in self._workers:
            policy.load_state_dict(self.master.state_dict())
        if self._proc_pool is not None:
            # forked workers carry the OLD buffers; rebuild with fresh state
            self._proc_pool.close()
            self._proc_pool = None

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._proc_pool is not None:
            self._proc_pool.close()
            self._proc_pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _flat(self) -> np.ndarray:
        import torch

        with torch.no_grad():
            vec = torch.nn.utils.parameters_to_vector(self.master.parameters())
        return vec.detach().cpu().numpy().astype(np.float32)

    def _load(self, policy, flat: np.ndarray) -> None:
        import torch

        with torch.no_grad():
            # .clone() is load-bearing: vector_to_parameters RE-POINTS each
            # param.data into views of the vector, and torch.from_numpy shares
            # memory with `flat` — without the clone, optimizer.step() would
            # silently mutate the caller's (immutable-by-contract) state array
            torch.nn.utils.vector_to_parameters(
                torch.from_numpy(np.ascontiguousarray(flat)).clone(),
                policy.parameters(),
            )

    def init_state(self, params_flat=None, key: int | None = None) -> HostState:
        flat = self._flat() if params_flat is None else np.asarray(params_flat, np.float32)
        return HostState(
            params_flat=flat,
            opt_state=None,
            key=self.seed if key is None else int(key),
            generation=0,
            sigma=self.sigma,
        )

    def compile(self, state: HostState) -> float:
        return 0.0  # nothing to compile on the host path

    compile_split = compile

    # ------------------------------------------------------------ noise math

    def _pair_offsets(self, state: HostState) -> np.ndarray:
        """Per-generation noise offsets; deterministic in (key, gen),
        mirroring the device engine's fold_in derivation.  One offset per
        antithetic PAIR when mirrored, one per MEMBER otherwise (the
        reference's plain per-member sampling)."""
        n = self.n_pairs if self.mirrored else self.population_size
        rng = np.random.default_rng(
            np.random.SeedSequence(entropy=state.key, spawn_key=(state.generation,))
        )
        return rng.integers(
            0, self.table_size - self.dim + 1, size=n, dtype=np.int64
        )

    def _eps(self, offset: int) -> np.ndarray:
        return self.table[offset : offset + self.dim]

    def _member_sign_off(self, offs: np.ndarray, i: int) -> tuple[float, int]:
        return member_sign_offset(offs, i, self.mirrored)

    def member_theta(self, state: HostState, member_index: int) -> np.ndarray:
        offs = self._pair_offsets(state)
        sign, off = self._member_sign_off(offs, member_index)
        return state.params_flat + self._state_sigma(state) * sign * self._eps(off)

    def _state_sigma(self, state: HostState) -> float:
        # pre-sigma-field states (e.g. hand-built in tests) fall back to init
        # σ; None (not 0.0) is the sentinel so a fully-decayed σ==0 is honored
        return self.sigma if state.sigma is None else float(state.sigma)

    # alias matching the device engine's name
    def member_params(self, state: HostState, member_index: int) -> np.ndarray:
        return self.member_theta(state, member_index)

    # ------------------------------------------------------------- rollouts

    @staticmethod
    def _call_rollout(agent, policy) -> HostRolloutResult:
        out = agent.rollout(policy)
        if isinstance(out, tuple):
            reward, bc = out[0], np.asarray(out[1], dtype=np.float32).reshape(-1)
        else:
            reward, bc = out, np.zeros(0, dtype=np.float32)
        steps = int(getattr(agent, "last_episode_steps", 0))
        return HostRolloutResult(float(reward), bc, steps)

    def _proc_evaluate(self, state: HostState, offs=None) -> HostEvalResult:
        from ..resilience.chaos import kill_workers
        from .procpool import ProcessPool

        if self._proc_pool is None or self._proc_pool.n_proc != self.n_proc:
            if self._proc_pool is not None:
                self._proc_pool.close()
            self._proc_pool = ProcessPool(
                self.policy_factory, self.agent_factory, self.n_proc,
                self.population_size, self.dim, self.table,
                master_state=self.master.state_dict(),
                mirrored=self.mirrored,
            )
        self._proc_pool.telemetry = self.telemetry
        # generation boundary: workers lost last generation come back now,
        # restoring full population participation (docs/resilience.md)
        self._proc_pool.respawn_dead()
        killed = kill_workers(state.generation, self._proc_pool.worker_pids)
        if killed:
            self.telemetry.counters.inc("chaos_worker_kills", len(killed))
            self.telemetry.event("chaos_worker_kill", pids=killed,
                                 gen=int(state.generation))
        if offs is None:
            offs = self._pair_offsets(state)
        fitness, bc, steps = self._proc_pool.evaluate(
            state.params_flat, self._state_sigma(state), offs,
            timeout_s=self.proc_timeout_s,
            generation=int(state.generation),
        )
        return HostEvalResult(fitness=fitness, bc=bc, steps=int(steps))

    def evaluate(self, state: HostState, offs=None) -> HostEvalResult:
        """Population evaluation.  ``offs`` lets generation_step hand in
        offsets it already derived under the ``sample`` span (the
        default None re-derives them — same deterministic values)."""
        if self.worker_mode == "process":
            return self._proc_evaluate(state, offs)
        if offs is None:
            offs = self._pair_offsets(state)
        sigma = self._state_sigma(state)
        results: list[HostRolloutResult | None] = [None] * self.population_size

        from ..resilience.chaos import member_fault

        def run_slice(w: int):
            policy, agent = self._workers[w]
            for i in range(w, self.population_size, self.n_proc):
                sign, off = self._member_sign_off(offs, i)
                theta = state.params_flat + sigma * sign * self._eps(off)
                self._load(policy, theta)
                try:
                    member_fault(state.generation, i)  # chaos injection
                    results[i] = self._call_rollout(agent, policy)
                except Exception:  # noqa: BLE001 — a dead member must not
                    # kill the generation (reference behavior: one worker
                    # exception hangs the whole MPI gather, SURVEY.md §5);
                    # NaN fitness marks the member for straggler-drop
                    # renormalization in utils/fault.py
                    results[i] = HostRolloutResult(
                        float("nan"), np.zeros(0, dtype=np.float32), 0
                    )

        if self.n_proc == 1:
            run_slice(0)
        else:
            list(self._pool.map(run_slice, range(self.n_proc)))

        fitness = np.array([r.total_reward for r in results], dtype=np.float32)
        bc_dim = max((r.bc.shape[0] for r in results), default=0)
        bc = np.zeros((self.population_size, bc_dim), dtype=np.float32)
        for i, r in enumerate(results):
            if r.bc.shape[0]:
                bc[i] = r.bc
        steps = int(sum(r.steps for r in results))
        return HostEvalResult(fitness=fitness, bc=bc, steps=steps)

    def evaluate_center(self, state: HostState) -> HostRolloutResult:
        policy, agent = self._workers[0]
        self._load(policy, state.params_flat)
        return self._call_rollout(agent, policy)

    # -------------------------------------------------------------- updates

    def apply_weights(self, state: HostState, weights,
                      offs=None) -> tuple[HostState, float]:
        """Folded mirrored-pair estimator + torch optimizer step (the
        reference's param.grad → optimizer.step() flow, SURVEY.md §3.2).

        Optimizer moments travel WITH the state (``opt_state`` holds the torch
        optimizer state_dict), so independent centers — the novelty family's
        meta-population — never blend Adam statistics through the shared
        master optimizer.
        """
        w = np.asarray(weights, dtype=np.float32)
        if offs is None:
            offs = self._pair_offsets(state)
        sigma = self._state_sigma(state)
        grad_ascent = np.zeros(self.dim, dtype=np.float32)
        if self.mirrored:
            pair_w = w[0::2] - w[1::2]  # fold_mirrored_weights, numpy edition
            for k, o in enumerate(offs):
                grad_ascent += pair_w[k] * self._eps(int(o))
        else:
            for i, o in enumerate(offs):
                grad_ascent += w[i] * self._eps(int(o))
        grad_ascent /= self.population_size * sigma
        return self.apply_grad(state, grad_ascent)

    def apply_grad(self, state: HostState,
                   grad_ascent: np.ndarray) -> tuple[HostState, float]:
        """Torch-optimizer step from an ALREADY-SCALED ascent direction
        (the 1/(n·σ) division is the caller's — apply_weights above, or
        the async scheduler's mixed-staleness fold, algo/scheduler.py).
        Weight decay, chaos update poisoning, σ annealing, and the
        immutable-state contract all live here so the two callers can
        never diverge."""
        import copy

        import torch

        sigma = self._state_sigma(state)
        if self.weight_decay > 0.0:
            # same L2 pull as the device engine's _update_from_weights
            grad_ascent = grad_ascent - self.weight_decay * state.params_flat
        from ..resilience.chaos import poison_update

        if poison_update(state.generation):
            # chaos: a poisoned update direction — the post-update anomaly
            # guard (ES.train on metrics["update_finite"]) must catch this
            grad_ascent = np.full_like(grad_ascent, np.nan)

        self._load(self.master, state.params_flat)
        if state.opt_state is not None:
            # deepcopy is load-bearing: load_state_dict keeps the INPUT
            # tensors when dtype/device already match, so the live
            # optimizer would alias state.opt_state and step() would
            # mutate the caller's (immutable-by-contract) state in place —
            # corrupting any rollback/rejection path that re-applies from
            # the same state (docs/resilience.md)
            self.optimizer.load_state_dict(copy.deepcopy(state.opt_state))
        else:
            # fresh center: reset any moments left by another state
            self.optimizer = self._optimizer_ctor(
                self.master.parameters(), **self._optimizer_kwargs
            )
        self.optimizer.zero_grad()
        # torch optimizers minimize: descend on -ascent
        g = torch.from_numpy(-np.ascontiguousarray(grad_ascent))
        i = 0
        for p in self.master.parameters():
            n = p.numel()
            p.grad = g[i : i + n].view_as(p).clone()
            i += n
        self.optimizer.step()

        new_sigma = sigma
        if self.sigma_decay != 1.0:
            # same multiplicative anneal + floor as the device engine
            new_sigma = max(sigma * self.sigma_decay, self.sigma_min)
        new_state = HostState(
            params_flat=self._flat(),
            opt_state=copy.deepcopy(self.optimizer.state_dict()),
            key=state.key,
            generation=state.generation + 1,
            sigma=new_sigma,
        )
        return new_state, float(np.linalg.norm(grad_ascent))

    def generation_step(self, state: HostState):
        from ..resilience.chaos import mutate_fitness
        from ..utils.fault import rank_weights_with_failures

        obs = self.telemetry
        # span taxonomy (docs/observability.md): sample = per-generation
        # noise-offset derivation (cheap BY DESIGN — the shared-table
        # scheme regenerates ε instead of storing it; a fat sample span
        # here means that design broke); eval = every member rollout;
        # update = rank transform + folded estimator + optimizer step
        with obs.phase("sample"):
            offs = self._pair_offsets(state)
        with obs.phase("eval"):
            ev = self.evaluate(state, offs=offs)
        fitness = mutate_fitness(state.generation, ev.fitness)
        n_valid = int(np.isfinite(np.asarray(fitness)).sum())
        base = {"fitness": fitness, "bc": ev.bc, "steps": ev.steps,
                "n_valid": n_valid}
        if n_valid < 2:
            # population collapse: not this layer's call to crash or retry —
            # state is untouched, n_valid reports it, and ES.train owns the
            # reject/re-run policy (docs/resilience.md failure model)
            return state, {**base, "grad_norm": float("nan"),
                           "update_finite": True}
        with obs.phase("update"):
            weights = rank_weights_with_failures(fitness)
            new_state, gnorm = self.apply_weights(state, weights, offs=offs)
        metrics = {
            **base,
            "grad_norm": gnorm,
            # post-update anomaly guard input: a non-finite parameter or
            # update norm means this generation must be rejected upstream,
            # not trained on
            "update_finite": bool(
                np.isfinite(gnorm)
                and np.isfinite(new_state.params_flat).all()
            ),
        }
        return new_state, metrics
