from .engine import HostEngine, HostEvalResult, HostRolloutResult, HostState

__all__ = ["HostEngine", "HostEvalResult", "HostRolloutResult", "HostState"]
