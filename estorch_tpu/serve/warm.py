"""Warm-start bundles: ship the bucket ladder's compiled XLA programs
WITH the artifact, so a fresh replica's first request never waits on JIT.

The compile ledger (PR 6) shows exactly where a fresh serving process
spends its startup: the batcher's construction-time bucket verification
compiles one batched program per ladder shape — a multi-second JIT storm
for a large policy, paid again by every replica the fleet spins up.
This module moves that cost to EXPORT time, once:

* :func:`warm_bundle` replays the exact serve-time load path (``load_
  bundle`` → predict-program builders → :func:`build_serving_batcher`
  with its verification pass) under a scoped redirect of jax's
  persistent XLA compilation cache into ``<bundle>/warm/`` — so the warm
  directory ends up holding precisely the executables a serving process
  will ask for, auxiliary one-op programs included (a "zero fresh builds
  at load" proof fails on any program left out);
* :func:`install_warmth` copies those entries into the serving process's
  active cache directory (or a process-scoped temp dir when none is
  configured) BEFORE any jax work, so every subsequent compile request
  is a persistent-cache retrieval.  The bundle itself is never written
  to — jax's cache touches per-entry atime files on read, and a bundle
  must stay immutable under its manifest checksums (possibly on a
  read-only mount).

Warmth is advisory, never load-bearing: entries key on the exact HLO +
jax version + platform, so a mismatch (new jax on the serving host, cpu
bundle on a tpu) simply misses and compiles fresh — ``install_warmth``
detects the foreseeable mismatches up front and reports a structured
reason instead of silently shipping dead weight into the cache dir.
The proof of warmth is counted, not assumed: the server snapshots the
jax build counters (``utils.backend.compile_event_counts``) around the
bundle load and publishes ``compiles_at_load`` / ``warm_cache_hits``.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Sequence

import numpy as np

from .batcher import DynamicBatcher
from .bundle import WARM_DIR, BundleError, _sha256_file, load_bundle

# The documented per-bucket accuracy bound for quantized serving: the
# worst row of the quantized program may deviate from the f32 anchor by
# at most this fraction of the anchor output's scale
# (serve/batcher.py::measure_quant_divergence defines the metric).
# bf16 keeps ~8 mantissa bits (~0.4% per rounding); two GEMM layers plus
# activations accumulate to low single-digit percents for well-scaled
# policies, so 5% separates "quantization noise" from "this policy
# amplifies rounding error" with margin on both sides.
BF16_DIVERGENCE_BOUND = 0.05


def build_serving_batcher(
    bundle,
    *,
    max_batch: int = 32,
    max_wait_ms: float = 4.0,
    max_queue: int = 256,
    dtype: str = "f32",
    quant_bound: float | None = None,
    telemetry=None,
) -> DynamicBatcher:
    """THE serve-time batcher construction — one definition shared by the
    server's engine build and the export-time warm replay, so the warm
    cache can never drift from what a serving process actually compiles.

    ``dtype="bf16"`` builds the quantized fast path next to the f32
    reference: the batcher measures per-bucket divergence and excludes
    drifting buckets (f32 fallback at the same shape); a bundle that did
    not opt in, or a policy past the bound at the anchor, raises
    :class:`BundleError` — the server's 409, the CLI's exit 2.
    """
    batch_fn = bundle.batched_predict_fn()  # refuses recurrent bundles
    quant_fn = None
    bound = None
    if dtype != "f32":
        quant_fn = bundle.batched_predict_fn(dtype=dtype)  # opt-in check
        bound = float(quant_bound if quant_bound is not None
                      else BF16_DIVERGENCE_BOUND)
    try:
        return DynamicBatcher(
            batch_fn, bundle.obs_shape, max_batch=max_batch,
            max_wait_ms=max_wait_ms, max_queue=max_queue,
            telemetry=telemetry, quant_fn=quant_fn, quant_bound=bound,
            quant_label=dtype,
        )
    except ValueError as e:
        # slot-dependent anchor or out-of-bound quantization: bundle-grade
        # rejections — /reload answers 409, the CLI exits 2
        raise BundleError(
            f"bundle at {bundle.path!r} cannot serve ({dtype}): {e}"
        ) from e


def _platform_facts() -> dict:
    import jax

    return {
        "jax_version": jax.__version__,
        "platform": jax.default_backend(),
        "device_count": len(jax.devices()),
    }


def warm_bundle(
    path: str,
    *,
    max_batch: int = 32,
    dtypes: Sequence[str] = ("f32",),
    quant_bound: float | None = None,
) -> tuple[dict, dict]:
    """Pre-trace + compile the bundle's serving programs into
    ``<bundle>/warm/`` and return ``(warm_block, sha_entries)`` for the
    manifest.  Called by ``export_bundle(warm=True)`` on an
    already-committed (cold) bundle; the caller re-commits the manifest
    with the returned block.

    Replays the REAL load path for every requested dtype: bundle load
    (auxiliary programs included), the batcher's bucket-verification
    storm (the ladder compiles), the quantized divergence measurement
    when a non-f32 dtype is warmed, the batch-1 GEMV leg, and the
    single-observation predict program — each compiled under a scoped
    cache redirect so exactly these executables land in the bundle.
    """
    from ..utils.backend import scoped_compilation_cache

    path = os.path.abspath(path)
    warm_dir = os.path.join(path, WARM_DIR)
    shutil.rmtree(warm_dir, ignore_errors=True)  # re-export: start clean
    t0 = time.perf_counter()
    buckets: list[int] = []
    excluded: list[int] = []
    with scoped_compilation_cache(warm_dir):
        import jax

        # the exporting process (it just trained) holds in-memory
        # executables for many auxiliary programs; those would NOT
        # recompile during the replay and so would never land in the
        # warm dir — then a fresh serving process would miss exactly
        # them.  Clearing forces every program the load path touches
        # through the (redirected) persistent cache.
        jax.clear_caches()
        bundle = load_bundle(path)
        obs_shape = bundle.obs_shape
        if bundle.recurrent:
            # recurrent bundles serve in-process only (no batcher): warm
            # the single-predict program and be done
            bundle.predict(np.zeros(obs_shape, np.float32))
        else:
            for dtype in dtypes:
                b = build_serving_batcher(bundle, max_batch=max_batch,
                                          dtype=dtype,
                                          quant_bound=quant_bound)
                if dtype == "f32":
                    buckets = list(b.buckets)
                    excluded = list(b.buckets_excluded)
                b.close(drain=True, timeout=10.0)
            # the --max-batch 1 leg (GEMV family) and the in-process
            # Bundle.predict program
            bundle.batched_predict_fn()(
                np.zeros((1,) + obs_shape, np.float32))
            bundle.predict(np.zeros(obs_shape, np.float32))
    # prune: atime files are jax's read-bookkeeping, recreated harmlessly
    # in the INSTALLED copy — shipping them would put mutable state under
    # an immutability checksum
    for fname in os.listdir(warm_dir):
        if fname.endswith("-atime"):
            os.remove(os.path.join(warm_dir, fname))
    entries: dict[str, int] = {}
    shas: dict[str, str] = {}
    for fname in sorted(os.listdir(warm_dir)):
        fpath = os.path.join(warm_dir, fname)
        entries[fname] = os.path.getsize(fpath)
        shas[f"{WARM_DIR}/{fname}"] = _sha256_file(fpath)
    if not entries:
        raise BundleError(
            "warm export produced no cache entries — the persistent XLA "
            "compilation cache is not functional on this jax build"
        )
    block = {
        "format": "xla_cache",
        "max_batch": int(max_batch),
        "buckets": buckets,
        "buckets_excluded": excluded,
        "dtypes": list(dtypes),
        "warm_s": round(time.perf_counter() - t0, 3),
        "entries": entries,
        **_platform_facts(),
    }
    if bundle.recurrent:
        # only the single-predict program exists — the ladder-complete
        # structural check does not apply
        block["recurrent_only"] = True
    return block, shas


_PROCESS_WARM_CACHE_DIR: str | None = None


def _process_warm_cache_dir() -> str:
    """A process-scoped cache dir for warmth installs when the process
    has no persistent cache configured — temp, cleaned at exit, so an
    ephemeral serving process never pollutes durable per-user state."""
    global _PROCESS_WARM_CACHE_DIR
    if _PROCESS_WARM_CACHE_DIR is None:
        import atexit
        import tempfile

        d = tempfile.mkdtemp(prefix="estorch_warm_cache_")
        atexit.register(shutil.rmtree, d, ignore_errors=True)
        _PROCESS_WARM_CACHE_DIR = d
    return _PROCESS_WARM_CACHE_DIR


def install_warmth(path: str, manifest: dict) -> dict:
    """Install a bundle's packed warmth into this process's persistent
    compilation cache; returns a structured status dict (never raises on
    incompatibility — a stale-warmth bundle is still a valid bundle):

    ``{"installed": bool, "reason": str|None, "entries": int,
       "cache_dir": str|None, "jax_version": str, "platform": str}``

    Must run BEFORE the process's first jax compilation of the serving
    programs — the server calls it at the top of its engine build.
    Mismatched jax version or platform means the cache keys cannot hit;
    that is a finding (the doctor's warm probe reports it too), not an
    error, and the process simply compiles fresh.
    """
    warm = manifest.get("warm")
    if not isinstance(warm, dict):
        return {"installed": False, "reason": "no warmth packed",
                "entries": 0, "cache_dir": None}
    facts = _platform_facts()
    out = {"installed": False, "entries": 0, "cache_dir": None,
           "jax_version": warm.get("jax_version"),
           "platform": warm.get("platform")}
    if warm.get("format") != "xla_cache":
        out["reason"] = (f"unknown warmth format {warm.get('format')!r} — "
                         "this version installs only 'xla_cache'")
        return out
    if warm.get("jax_version") != facts["jax_version"]:
        out["reason"] = (
            f"warmth was built under jax {warm.get('jax_version')}, this "
            f"process runs {facts['jax_version']} — cache keys cannot "
            "match; ignoring warmth (re-export the bundle with warm=True "
            "under the serving jax version)")
        return out
    if warm.get("platform") != facts["platform"]:
        out["reason"] = (
            f"warmth was compiled for platform {warm.get('platform')!r}, "
            f"this process runs {facts['platform']!r} — executables are "
            "not portable across backends; ignoring warmth")
        return out
    from ..utils.backend import (current_compilation_cache_dir,
                                 enable_compilation_cache)

    cache_dir = current_compilation_cache_dir()
    if cache_dir is None:
        cache_dir = enable_compilation_cache(_process_warm_cache_dir())
    warm_dir = os.path.join(os.path.abspath(path), WARM_DIR)
    n = 0
    for fname in warm.get("entries", {}):
        src = os.path.join(warm_dir, fname)
        dst = os.path.join(cache_dir, fname)
        if not os.path.exists(dst):
            shutil.copy2(src, dst)
        n += 1
    out["installed"] = True
    out["entries"] = n
    out["cache_dir"] = cache_dir
    if warm.get("device_count") != facts["device_count"]:
        out["note"] = (
            f"warmth was exported with {warm.get('device_count')} "
            f"device(s), this process has {facts['device_count']} — "
            "single-device serving programs usually still hit, but "
            "cross-process bit parity wants matching --cpu-devices anyway")
    return out
