"""estorch_tpu.serve — versioned policy bundles + dynamic-batching
inference server (docs/serving.md).

The serving vertical: export a trained policy into a self-describing
bundle (serve/bundle.py), serve it behind a dynamic micro-batcher
(serve/batcher.py, serve/server.py), drive it (serve/client.py,
serve/loadgen.py).

Heavy submodules (bundle/predictor/server pull jax+flax) load lazily via
PEP 562 so light consumers — doctor's serve checks, the loadgen smoke —
can import this package without paying for, or wedging on, a device
runtime.
"""

from __future__ import annotations

from .batcher import (BatchError, BatcherClosed, BatcherSaturated,
                      DynamicBatcher, bucket_sizes)
from .client import ServeClient, ServeError

_LAZY = {
    # fleet front door (stdlib, jax-free — lazy only for symmetry)
    "Router": "router",
    "CircuitBreaker": "router",
    "Fleet": "fleet",
    "FleetError": "fleet",
    "load_fleet_config": "fleet",
    "Bundle": "bundle",
    "BundleError": "bundle",
    "export_bundle": "bundle",
    "load_bundle": "bundle",
    "validate_bundle": "bundle",
    "make_single_predict": "predictor",
    "make_batched_predict": "predictor",
    "PolicyServer": "server",
    "find_free_port": "server",
    "run_load": "loadgen",
    "coldstart_probe": "loadgen",
    "BF16_DIVERGENCE_BOUND": "warm",
    "build_serving_batcher": "warm",
    "warm_bundle": "warm",
    "install_warmth": "warm",
}

__all__ = [
    "BatchError",
    "BatcherClosed",
    "BatcherSaturated",
    "DynamicBatcher",
    "bucket_sizes",
    "ServeClient",
    "ServeError",
    *sorted(_LAZY),
]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
