"""Threaded inference server over a policy bundle (stdlib HTTP only).

``python -m estorch_tpu.serve --bundle <dir>`` serves:

* ``POST /predict``  — ``{"obs": [...]}`` → ``{"action": [...]}``; the
  request rides the dynamic micro-batcher (serve/batcher.py); a full
  queue answers 503 + ``Retry-After`` instead of growing without bound;
* ``GET /healthz``   — liveness + the PR-2 heartbeat facts (last phase,
  beat age) + queue/counter snapshot; 503 while draining;
* ``GET /stats``     — full serving counters, bucket ladder, bundle
  provenance;
* ``GET /metrics``   — Prometheus text exposition of the same counters
  (obs/export/prometheus.py) + heartbeat freshness, for scrapers;
* ``POST /reload``   — ``{"path": "<bundle dir>"}`` hot-swaps the bundle
  atomically: the new bundle loads and warms OFF the serving path, the
  swap is one reference assignment, and the old batcher drains its
  in-flight requests against the old params — no request ever sees a
  half-loaded policy.

Operational contract (docs/serving.md): heartbeat beats ride the
``ESTORCH_OBS_HEARTBEAT`` protocol (obs/recorder.py) so the PR-3
watchdog machinery can babysit a serving process exactly like a training
run — ``serve --supervised`` runs the server as a spawned child of
:class:`estorch_tpu.resilience.Supervisor` with heartbeat-staleness
restarts.  SIGTERM drains: stop accepting, answer everything in flight,
write the final counter line, exit 0.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..obs.spans import Telemetry, resolve_telemetry
from ..obs.tracing import (PARENT_SPAN_HEADER, SAMPLED_HEADER,
                           TRACES_FILENAME, ProcessTracer, make_segment,
                           traces_payload)
from .batcher import BatcherClosed, BatcherSaturated, DynamicBatcher
from .bundle import BundleError, load_bundle

DRAIN_GRACE_S = 15.0


class _Engine:
    """One immutable (bundle, batcher) pair — THE hot-reload swap unit."""

    def __init__(self, bundle, batcher: DynamicBatcher):
        self.bundle = bundle
        self.batcher = batcher


class PolicyServer:
    """Bundle + dynamic batcher behind a ThreadingHTTPServer."""

    def __init__(
        self,
        bundle_path: str,
        *,
        host: str = "127.0.0.1",
        port: int = 8321,
        max_batch: int = 32,
        max_wait_ms: float = 4.0,
        max_queue: int = 256,
        request_timeout_s: float = 30.0,
        telemetry=None,
        warm: bool = False,
        dtype: str = "f32",
        warm_install: bool = True,
        quant_bound: float | None = None,
        t0_monotonic: float | None = None,
        run_dir: str | None = None,
        trace_head_every: int = 16,
    ):
        self.obs = resolve_telemetry(telemetry)
        self.max_batch = int(max_batch)
        # validate the CONFIG here so a bad --max-batch fails fast as a
        # config error — inside _build_engine it would be misattributed
        # to the bundle (the try there is for slot-dependence only)
        from .batcher import bucket_sizes

        bucket_sizes(self.max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.request_timeout_s = float(request_timeout_s)
        self.warm = bool(warm)
        from .predictor import SERVE_DTYPES

        if dtype not in SERVE_DTYPES:
            raise BundleError(
                f"serving dtype must be one of {SERVE_DTYPES}, got "
                f"{dtype!r}")
        self.dtype = dtype
        self.warm_install = bool(warm_install)
        self.quant_bound = quant_bound
        # monotonic: uptime is an elapsed measure (esguard R09 — an NTP
        # step must not make a healthy server report negative uptime)
        # t0_monotonic: the CLI stamps it at main() entry so startup_s
        # covers the jax import, not just this constructor
        self._started_mono = (time.monotonic() if t0_monotonic is None
                              else float(t0_monotonic))
        self._first_request_recorded = False
        self._first_request_lock = threading.Lock()
        self.draining = False
        # per-request trace ids (docs/observability.md "Tails & traces"):
        # minted at HTTP entry, threaded through the batcher's recorder
        # events, echoed back as the X-Trace-Id response header
        self._req_seq = itertools.count(1)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._inflight_zero = threading.Event()
        self._inflight_zero.set()
        self._drained = threading.Event()
        self.obs.note("load_bundle")
        # serializes reload-vs-reload and reload-vs-shutdown: concurrent
        # swaps would double-close one old engine and leak the other
        self._engine_lock = threading.Lock()
        self._engine = self._build_engine(bundle_path)
        # cold-start facts (docs/serving.md "Cold start & quantized
        # serving"): gauges so /metrics, the heartbeat, and the fleet
        # dash all see how this replica came up
        self.obs.counters.gauge(
            "startup_s", round(time.monotonic() - self._started_mono, 3))
        self._httpd = _Httpd((host, int(port)), _make_handler(self))
        self.host, self.port = self._httpd.server_address[:2]
        # per-hop trace segments + tail sampler (obs/tracing.py,
        # docs/observability.md "Distributed tracing"): proc is
        # port-qualified so fleet replicas land in distinct lanes of the
        # assembled trace.  The batcher shares this tracer — its
        # lifecycle child segments must ride the SAME tail verdict the
        # handler applies at response time.
        self.tracer = ProcessTracer(
            f"server-{self.port}", counters=self.obs.counters,
            hists=self.obs.hists, hist_name="serve/request_s",
            head_every=trace_head_every,
            path=(os.path.join(run_dir, TRACES_FILENAME) if run_dir
                  else None))
        self._engine.batcher.tracer = self.tracer

    # ----------------------------------------------------------- engine

    def _build_engine(self, bundle_path: str) -> _Engine:
        # count XLA executable builds across the load: fresh builds vs
        # persistent-cache retrievals is THE warm-bundle proof (a warm
        # load is all hits; utils/backend.py explains the event stream)
        from ..utils.backend import (compile_event_counts,
                                     install_compile_event_counters)
        from .warm import build_serving_batcher

        counted = install_compile_event_counters()
        before = compile_event_counts()
        t0 = time.perf_counter()
        bundle = load_bundle(bundle_path, install_warm=self.warm_install)
        # the batcher's construction-time bucket verification doubles as
        # the compile warm-up for every ladder shape (serve/batcher.py);
        # --warm additionally pre-compiles the single-bucket case the
        # verification skips (max_batch=1, the A/B baseline)
        batcher = build_serving_batcher(
            bundle, max_batch=self.max_batch, max_wait_ms=self.max_wait_ms,
            max_queue=self.max_queue, dtype=self.dtype,
            quant_bound=self.quant_bound, telemetry=self.obs,
        )
        if self.warm and len(batcher.buckets) == 1:
            b = batcher.buckets[0]
            batcher.batch_fn(np.zeros((b,) + bundle.obs_shape, np.float32))
        # hot reload swaps in a fresh batcher mid-flight: it must keep
        # feeding the same per-process tracer (None during the FIRST
        # build — __init__ assigns once the bound port names the proc)
        batcher.tracer = getattr(self, "tracer", None)
        dt = time.perf_counter() - t0
        after = compile_event_counts()
        warm_installed = bool(bundle.warm_status
                              and bundle.warm_status.get("installed"))
        if counted:
            hits = int(after["cache_hits"] - before["cache_hits"])
            fresh = int(after["programs"] - before["programs"]) - hits
        else:  # no monitoring stream on this jax build: warmth unproven
            hits, fresh = 0, None
        self.obs.counters.gauge("warm_cache_hits", hits)
        self.obs.counters.gauge(
            "compiles_at_load", -1 if fresh is None else fresh)
        self.obs.compile_event(
            "bundle_load", dt, count_recompiles=0, first_call=True,
            cache_hits=hits, fresh_builds=fresh,
            warm_installed=warm_installed,
            **({"warm_skip_reason": bundle.warm_status["reason"]}
               if bundle.warm_status and bundle.warm_status.get("reason")
               else {}))
        return _Engine(bundle, batcher)

    def reload(self, bundle_path: str) -> dict:
        """Hot bundle reload: load+warm off-path, swap atomically, drain
        the old batcher.  On any load error the old bundle keeps serving.
        Serialized: concurrent reloads would double-close one old engine
        and leak the other's worker thread + loaded params."""
        with self._engine_lock:
            if self.draining:
                raise BundleError("server is draining — reload refused")
            old = self._engine
            new = self._build_engine(bundle_path)  # BundleError on junk
            self._engine = new  # atomic reference swap
        self.obs.counters.inc("reloads_total")
        self.obs.event("bundle_reloaded", path=bundle_path,
                       version=new.bundle.version)
        old.batcher.close(drain=True)
        return {"ok": True, "version": new.bundle.version,
                "previous": old.bundle.version}

    # ---------------------------------------------------------- serving

    def predict(self, obs, trace: str | None = None,
                span: str | None = None) -> np.ndarray:
        # one engine read per attempt; a request racing a hot reload can
        # catch the OLD batcher mid-close (BatcherClosed) on a perfectly
        # healthy server — retry against the freshly-swapped engine
        # instead of answering a spurious "draining" 503
        while True:
            eng = self._engine
            try:
                out = eng.batcher.predict(obs,
                                          timeout=self.request_timeout_s,
                                          trace=trace, span=span)
            except BatcherClosed:
                if self.draining or eng is self._engine:
                    raise
                continue
            if not self._first_request_recorded:
                # time-to-first-response from process start — THE
                # cold-start product metric; set once, raced safely
                with self._first_request_lock:
                    if not self._first_request_recorded:
                        self._first_request_recorded = True
                        self.obs.counters.gauge(
                            "first_request_s",
                            round(time.monotonic() - self._started_mono,
                                  3))
            return out

    def track_request(self):
        with self._inflight_lock:
            self._inflight += 1
            self._inflight_zero.clear()

    def untrack_request(self):
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._inflight_zero.set()

    def health(self) -> dict:
        eng = self._engine
        c = self.obs.counters
        out = {
            "ok": not self.draining,
            "draining": self.draining,
            "version": eng.bundle.version,
            "bundle": eng.bundle.path,
            "uptime_s": round(time.monotonic() - self._started_mono, 3),
            "pid": os.getpid(),
            "queue_depth": eng.batcher._q.qsize(),
            "requests_total": int(c.get("requests_total")),
            "shed_total": int(c.get("shed_total")),
        }
        hb = self.obs.heartbeat
        if hb is not None:
            from ..obs.recorder import read_heartbeat

            beat = read_heartbeat(hb.path)
            if beat is not None:
                out["heartbeat"] = {"path": hb.path,
                                    "age_s": round(beat["age_s"], 3),
                                    "phase": beat.get("phase")}
        return out

    def metrics(self) -> str:
        """Prometheus text exposition of the serving counters (the
        `/metrics` body; obs/export/prometheus.py).  `estorch_up` is 1
        while not draining — this process answering IS the liveness; the
        heartbeat facts ride along when a heartbeat path is configured
        so scrapes and the PR-3 watchdog agree on staleness."""
        from ..obs.export.prometheus import render_exposition
        from ..obs.recorder import read_heartbeat

        eng = self._engine
        hb = (read_heartbeat(self.obs.heartbeat.path)
              if self.obs.heartbeat is not None else None)
        return render_exposition(
            self.obs.counters.snapshot(), hb,
            extra_gauges={
                "queue_depth": eng.batcher._q.qsize(),
                "uptime_seconds": round(
                    time.monotonic() - self._started_mono, 3),
                "draining": 1.0 if self.draining else 0.0,
            },
            up=not self.draining,
            # per-request lifecycle distributions (serve/batcher.py:
            # queue-wait, coalesce-wait, compute, request; the handler's
            # write) as true histogram types — the tail a scraper can
            # actually alert on
            histograms=self.obs.hists.export() or None,
        )

    def _collector_target(self) -> dict:
        """Ready-to-paste targets.json entry.  A wildcard bind address
        (0.0.0.0 / ::) is not routable FROM the collector's host — an
        operator pasting it would scrape the collector's own loopback —
        so substitute this machine's name, which is what a remote
        collector must dial anyway."""
        host = self.host
        if host in ("0.0.0.0", "::", ""):
            import socket as _socket

            host = _socket.getfqdn() or _socket.gethostname()
        return {
            "name": f"serve-{host}-{self.port}",
            "url": f"http://{host}:{self.port}/metrics",
        }

    def cold_start(self) -> dict:
        """The replica's cold-start facts (docs/serving.md): how long to
        come up, how long to first answer, and the warm-bundle proof —
        fresh XLA builds vs cache hits at load."""
        c = self.obs.counters
        fresh = c.get("compiles_at_load", -1)
        eng = self._engine
        out = {
            "startup_s": c.get("startup_s") or None,
            "first_request_s": (c.get("first_request_s")
                                if self._first_request_recorded else None),
            "compiles_at_load": None if fresh < 0 else int(fresh),
            "warm_cache_hits": int(c.get("warm_cache_hits")),
            "warm": eng.bundle.warm_status
            or {"installed": False, "reason": "no warmth packed"},
        }
        return out

    def stats(self) -> dict:
        eng = self._engine
        return {
            "version": eng.bundle.version,
            "bundle": eng.bundle.path,
            "source": eng.bundle.manifest.get("source"),
            "obs_shape": list(eng.bundle.obs_shape),
            "dtype": self.dtype,
            "cold_start": self.cold_start(),
            "max_wait_ms": self.max_wait_ms,
            "counters": self.obs.counters.snapshot(),
            # collector-discovery stanza (obs/agg/, docs/observability.md
            # "Fleet aggregation"): a ready-to-paste targets.json entry,
            # so enrolling this replica in the fleet collector is a copy,
            # not a transcription
            "collector_target": self._collector_target(),
            **eng.batcher.stats(),
        }

    # -------------------------------------------------------- lifecycle

    def serve_forever(self) -> None:
        self.obs.note("serving")
        self._httpd.serve_forever(poll_interval=0.1)

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, name="serve-http",
                             daemon=True)
        t.start()
        return t

    def shutdown(self, drain: bool = True) -> dict:
        """Graceful stop: no new connections, answer everything already
        in flight, drain the batcher queue, then close.  Returns the
        final counter snapshot (the CLI prints it as the last line)."""
        with self._engine_lock:
            # after this flag no reload can swap in a fresh engine that
            # shutdown would never close
            self.draining = True
        self.obs.note("draining")
        self._httpd.shutdown()  # stop accepting; serve_forever returns
        # requests already parsed (tracked) finish against the batcher
        self._inflight_zero.wait(DRAIN_GRACE_S)
        self._engine.batcher.close(drain=drain)
        self._httpd.server_close()
        self.tracer.flush()  # sampled segments outlive the process
        self.obs.note("drained")
        final = {
            "drained": True,
            "clean": self._inflight_zero.is_set(),
            "counters": self.obs.counters.snapshot(),
        }
        self._drained.set()
        return final


class _Httpd(ThreadingHTTPServer):
    # handler threads die with the process; drain correctness comes from
    # the in-flight tracking in PolicyServer.shutdown, not thread joins
    daemon_threads = True
    allow_reuse_address = True


def _make_handler(server: PolicyServer):
    class ServeHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # keep-alive: persistent clients

        def log_message(self, *args):  # quiet: obs counters tell the story
            pass

        def _reply(self, code: int, payload: dict,
                   extra_headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            if server.draining:
                # finish this response, then let the connection close so
                # keep-alive clients re-resolve elsewhere
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()
            self.wfile.write(body)

        # ------------------------------------------------------- routes

        def do_GET(self):
            if self.path == "/healthz":
                h = server.health()
                self._reply(200 if h["ok"] else 503, h)
            elif self.path == "/stats":
                self._reply(200, server.stats())
            elif self.path.split("?", 1)[0] == "/traces":
                # sampled segments since a cursor + histogram exemplars
                # (obs/tracing.py traces_payload) — the collector's
                # scrape leg of cross-process trace assembly
                q = self.path.split("since=", 1)
                try:
                    since = int(q[1].split("&", 1)[0]) if len(q) == 2 else 0
                except ValueError:
                    since = 0
                self._reply(200, traces_payload(server.tracer, since,
                                                hists=server.obs.hists))
            elif self.path == "/metrics":
                body = server.metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                if server.draining:
                    self.send_header("Connection", "close")
                    self.close_connection = True
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"no route {self.path!r}"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                data = json.loads(self.rfile.read(n)) if n else {}
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad request body: {e}"})
                return
            if not isinstance(data, dict):
                self._reply(400, {"error": "request body must be a JSON "
                                           "object"})
                return
            if self.path == "/predict":
                self._predict(data)
            elif self.path == "/reload":
                self._reload(data)
            else:
                self._reply(404, {"error": f"no route {self.path!r}"})

        def _predict(self, data: dict) -> None:
            if "obs" not in data:
                self._reply(400, {"error": "predict needs {'obs': [...]}"})
                return
            # a request counts as in flight until its RESPONSE is written:
            # untracking before the reply would let a SIGTERM drain declare
            # victory (inflight==0) while this thread still holds an
            # unwritten answer — and the process exit would drop it.
            # An incoming X-Trace-Id (the fleet router forwards the id it
            # minted) is honored so one slow answer traces end to end;
            # direct clients still get a locally-minted r<N>
            trace = (self.headers.get("X-Trace-Id")
                     or f"r{next(server._req_seq)}")
            # span parenting crosses the process boundary here: the
            # router's upstream LEG span arrives as X-Parent-Span, and an
            # upstream hop that already judged the trace interesting
            # (retry/hedge legs) forces this process's tail sampler
            parent_span = self.headers.get(PARENT_SPAN_HEADER) or None
            forced = self.headers.get(SAMPLED_HEADER) == "1"
            req_span = server.tracer.span_id()
            t0 = time.perf_counter()
            status, shed = 500, False
            headers = {"X-Trace-Id": trace}
            server.track_request()
            try:
                try:
                    out = server.predict(data["obs"], trace=trace,
                                         span=req_span)
                except BatcherSaturated:
                    status, shed = 503, True
                    self._reply(503,
                                {"error": "saturated — retry with backoff",
                                 "trace": trace},
                                {"Retry-After": "1", **headers})
                    return
                except BatcherClosed:
                    status = 503
                    self._reply(503, {"error": "draining"}, headers)
                    return
                except (ValueError, TypeError) as e:
                    # malformed obs AT SUBMIT (wrong shape → ValueError,
                    # nulls/non-numerics → TypeError from np.asarray) —
                    # genuinely the client's fault; batch-side faults
                    # arrive as BatchError below, never these types
                    status = 400
                    self._reply(400, {"error": str(e)}, headers)
                    return
                except TimeoutError as e:
                    status = 504
                    self._reply(504, {"error": str(e)}, headers)
                    return
                except Exception as e:  # noqa: BLE001 — a server fault
                    # (BatchError from the jitted forward, device runtime
                    # death) must answer 500, not drop the connection
                    server.obs.counters.inc("http_500_total")
                    server.obs.event("predict_error", error=repr(e)[:200],
                                     trace=trace)
                    self._reply(500, {"error": f"server fault: {e}"},
                                headers)
                    return
                t_write = time.perf_counter()
                self._reply(200, {"action": out.tolist()}, headers)
                status = 200
                # the write leg of the lifecycle (serialize + socket):
                # the only piece the batcher's request_s cannot see
                dt_write = time.perf_counter() - t_write
                server.obs.hists.observe("serve/write_s", dt_write)
                server.tracer.add(make_segment(
                    trace, server.tracer.span_id(), req_span,
                    server.tracer.proc, "write", t_write, dt_write))
            finally:
                # the request ROOT span + the tail verdict — recorded
                # last so every child (batcher lifecycle, write) is
                # already buffered under this trace id
                dur = time.perf_counter() - t0
                server.tracer.add(make_segment(
                    trace, req_span, parent_span, server.tracer.proc,
                    "request", t0, dur, attrs={"status": status}))
                server.tracer.finish(trace, dur, error=status >= 400,
                                     shed=shed, forced=forced)
                server.untrack_request()

        def _reload(self, data: dict) -> None:
            path = data.get("path")
            if not path:
                self._reply(400, {"error": "reload needs {'path': ...}"})
                return
            try:
                self._reply(200, server.reload(path))
            except (BundleError, OSError) as e:
                # the old bundle keeps serving — a bad reload is a 409,
                # not an outage
                self._reply(409, {"error": str(e)})

    return ServeHandler


# ---------------------------------------------------------------- CLI body

def run_server(args) -> int:
    """The ``python -m estorch_tpu.serve`` body (args from __main__.py).
    Returns the process exit code: 0 after a clean drain."""
    telemetry = Telemetry.from_env()
    telemetry.note("init")
    server = PolicyServer(
        args.bundle, host=args.host, port=args.port,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue, telemetry=telemetry, warm=args.warm,
        dtype=args.dtype, warm_install=not args.no_warm,
        t0_monotonic=getattr(args, "_t0_monotonic", None),
        run_dir=getattr(args, "run_dir", None),
    )

    stop = threading.Event()

    def _on_signal(signum, frame):
        del frame
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    url = f"http://{server.host}:{server.port}"
    ready = {
        "ready": True, "url": url, "pid": os.getpid(),
        "version": server._engine.bundle.version,
        "max_batch": server.max_batch,
        "buckets": list(server._engine.batcher.buckets),
        "dtype": server.dtype,
        "cold_start": server.cold_start(),
    }
    print(json.dumps(ready), flush=True)
    if args.port_file:
        from .router import write_port_file

        write_port_file(args.port_file, server.host, server.port)

    server.start_background()
    beat_s = max(0.2, float(args.beat_interval))
    while not stop.wait(beat_s):
        # periodic heartbeat so the PR-3 staleness watchdog sees an IDLE
        # server as alive, not wedged (batcher phases beat under load)
        telemetry.note("serving")
    final = server.shutdown(drain=True)
    print(json.dumps(final, default=float), flush=True)
    return 0 if final["clean"] else 1


# ------------------------------------------------------------- supervision

def supervised_child(root: str, argv: list) -> None:
    """Child body for ``serve --supervised`` — runs in a spawned (fresh)
    interpreter with ``ESTORCH_OBS_HEARTBEAT`` already pointed into
    ``root`` by the Supervisor plumbing (resilience/supervisor.py), so
    platform policy must be re-applied here before jax initializes."""
    del root
    t0 = time.monotonic()
    from .__main__ import build_parser

    args = build_parser().parse_args(argv)
    args._t0_monotonic = t0
    if args.cpu_devices > 0:
        from ..utils import force_cpu_backend

        force_cpu_backend(args.cpu_devices)
    raise SystemExit(run_server(args))


def run_supervised(args, argv: list) -> int:
    """Babysit the server with the PR-3 watchdog: exit-status + heartbeat
    staleness restarts, exponential backoff.  SIGTERM to the supervisor
    forwards to the child, which drains and exits 0 — the supervisor then
    reports clean completion."""
    from ..resilience.supervisor import Supervisor

    child_argv = [a for a in argv if a != "--supervised"]
    sup = Supervisor(
        ckpt_root=args.supervise_root,
        target_generation=0,
        child_target="estorch_tpu.serve.server:supervised_child",
        child_args=(child_argv,),
        max_restarts=args.max_restarts,
        stale_after_s=args.stale_after_s,
        startup_grace_s=args.startup_grace_s,
    )

    def _forward(signum, frame):
        del frame
        sup.request_stop(signum)

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)
    result = sup.run()
    print(json.dumps({"supervised": True, "ok": result["ok"],
                      "restarts": len(result["restarts"]),
                      "reason": result["reason"]}), flush=True)
    return 0 if result["ok"] else 1


def find_free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port for tests/tools (bind(0), read, release)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
