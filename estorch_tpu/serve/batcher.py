"""Dynamic micro-batcher: coalesce concurrent predict requests into
power-of-two buckets for one jitted batched forward per bucket shape.

The training insight applied to serving (PAPERS.md 2206.08888): the same
vectorized batched inference that evaluates a population evaluates
concurrent user requests — one weight-streaming GEMM amortizes the
memory traffic that dominates per-request GEMV on a CPU/TPU host.

Mechanics:

* a bounded queue feeds ONE worker thread; the worker takes the oldest
  request, then coalesces more until ``max_batch`` or ``max_wait_ms``
  from the first request, whichever comes first;
* the batch is padded to the next power-of-two bucket so the jitted
  predict compiles once per bucket — ``recompiles`` stays ≤ the number
  of ladder shapes no matter how request sizes mix;
* buckets start at 2 (when ``max_batch`` ≥ 2): batch-1 lowers to a GEMV
  whose final bits differ from the GEMM family, and a response's bits
  must not depend on how many neighbors a request was coalesced with
  (docs/serving.md "Bit-exactness contract").  Cross-shape row
  stability is MEASURED per loaded policy, not assumed — buckets whose
  rows deviate from the anchor (largest) bucket are excluded from the
  ladder at construction (:func:`verify_stable_buckets`);
* admission control: a full queue SHEDS (``BatcherSaturated`` →
  HTTP 503 + ``shed_total``) instead of growing without bound — graceful
  backpressure, not OOM;
* optional quantized fast path (``quant_fn``/``quant_bound``): per-bucket
  divergence vs the f32 anchor is MEASURED at construction
  (:func:`measure_quant_divergence`); out-of-bound buckets dispatch the
  exact f32 program instead, and a policy past the bound at the anchor
  is refused (docs/serving.md "Cold start & quantized serving");
* ``close(drain=True)`` stops intake, finishes every queued request, and
  joins the worker — the SIGTERM drain path.

Deliberately jax-free: ``batch_fn`` is any ``(B, *obs_shape) ndarray →
(B, ...) ndarray`` callable (``Bundle.batched_predict_fn()`` in
production, plain numpy in doctor's smoke test).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Sequence

import numpy as np

from ..obs.spans import NULL_TELEMETRY
from ..obs.tracing import make_segment


class BatcherClosed(RuntimeError):
    """submit() after close() — the server is draining."""


class BatcherSaturated(RuntimeError):
    """Queue full: request shed for backpressure (serve as HTTP 503)."""


class BatchError(RuntimeError):
    """The batched predict callable itself failed — a SERVER-side fault
    (device runtime error, poisoned params), distinct from the
    ValueError a caller's malformed observation raises at submit time.
    The server maps this to HTTP 500, never 400."""


class _Pending:
    """One in-flight request: the caller blocks on ``event``.

    Carries its own lifecycle clock marks (submit → taken off the queue
    → dispatched) and an optional caller-assigned trace id, so the
    per-request histograms (``serve/queue_wait_s``,
    ``serve/coalesce_wait_s``, ``serve/request_s``) and the flight
    recorder can tell WHICH request a tail sample belongs to."""

    __slots__ = ("obs", "event", "result", "error", "trace", "span",
                 "t_submit", "t_taken")

    def __init__(self, obs: np.ndarray, trace: str | None = None,
                 span: str | None = None):
        self.obs = obs
        self.event = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.trace = trace
        # the server's `request` span id: the parent the batcher's
        # queue_wait/coalesce/compute child segments hang under
        self.span = span
        self.t_submit = time.perf_counter()
        self.t_taken = 0.0


def bucket_sizes(max_batch: int) -> tuple[int, ...]:
    """The power-of-two bucket ladder for ``max_batch``.

    ``max_batch=1`` → ``(1,)`` (the batch-size-1 baseline); otherwise
    buckets start at 2 (GEMM family, see module docstring) and double up
    to ``max_batch`` (which must then itself be a power of two ≥ 2).
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if max_batch == 1:
        return (1,)
    if max_batch & (max_batch - 1):
        raise ValueError(
            f"max_batch must be a power of two (bucket ladder), got "
            f"{max_batch}"
        )
    out = []
    b = 2
    while b <= max_batch:
        out.append(b)
        b *= 2
    return tuple(out)


def verify_stable_buckets(
    batch_fn: Callable[[np.ndarray], np.ndarray],
    obs_shape: Sequence[int],
    buckets: Sequence[int],
    *,
    trials: int = 3,
    seed: int = 0,
) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Partition the bucket ladder into (stable, excluded) by MEASUREMENT.

    The serving bit-determinism contract — a request's bits must not
    depend on which bucket/neighbors it was coalesced with — rests on
    XLA producing row-identical results across batch shapes.  That holds
    for the GEMM family at most sizes but is NOT guaranteed: measured on
    CPU, the B=2 lowering can differ from B≥4 by 1 ulp for some trained
    parameter values.  So the contract is VERIFIED per loaded bundle
    instead of assumed: every bucket's rows are checked (random obs,
    random slot arrangements, real pad rows) against the largest bucket
    — the anchor — and buckets that fail are excluded from the ladder
    (their requests pad up to the next stable size).  The anchor itself
    is checked for slot-independence; if even that fails, serving cannot
    be made deterministic under coalescing and this raises.
    """
    buckets = sorted(set(int(b) for b in buckets))
    anchor = buckets[-1]
    rng = np.random.default_rng(seed)
    shape = tuple(int(d) for d in obs_shape)
    obs = rng.standard_normal((anchor,) + shape).astype(np.float32)
    ref = np.asarray(batch_fn(obs), np.float32)
    # anchor slot-independence: the same rows, shuffled, must yield the
    # same per-row bits
    for _ in range(trials):
        perm = rng.permutation(anchor)
        out = np.asarray(batch_fn(obs[perm]), np.float32)
        if out.tobytes() != ref[perm].tobytes():
            raise ValueError(
                f"batched predict is slot-dependent at anchor batch "
                f"{anchor}: the same observation yields different bits in "
                "different slots — deterministic coalesced serving is "
                "impossible with this program"
            )
    stable, excluded = [], []
    for b in buckets[:-1]:
        ok = True
        for _ in range(trials):
            idx = rng.choice(anchor, size=b, replace=False)
            out = np.asarray(batch_fn(obs[idx]), np.float32)
            if out.tobytes() != ref[idx].tobytes():
                ok = False
                break
            # half-full composition: real rows + zero padding
            n = max(1, b // 2)
            idx2 = rng.choice(anchor, size=n, replace=False)
            pad = np.zeros((b,) + shape, np.float32)
            pad[:n] = obs[idx2]
            out2 = np.asarray(batch_fn(pad), np.float32)[:n]
            if out2.tobytes() != ref[idx2].tobytes():
                ok = False
                break
        (stable if ok else excluded).append(b)
    stable.append(anchor)
    return tuple(stable), tuple(excluded)


def measure_quant_divergence(
    quant_fn: Callable[[np.ndarray], np.ndarray],
    batch_fn: Callable[[np.ndarray], np.ndarray],
    obs_shape: Sequence[int],
    buckets: Sequence[int],
    *,
    trials: int = 2,
    seed: int = 0,
) -> dict[int, float]:
    """Per-bucket divergence of the quantized program vs the f32 anchor —
    the :func:`verify_stable_buckets` discipline applied to accuracy.

    The f32 anchor rows are THE reference (they are what the f32 ladder's
    own bit-determinism contract chains to), and the quantized path's
    error is MEASURED against them per bucket: random obs drawn once at
    the anchor shape, each bucket fed row subsets, and the divergence
    reported as  ``max |quant - f32| / max(|f32 anchor rows|)``  — a
    relative-to-output-scale worst-row error.  Measuring per bucket (not
    once) matters because it captures BOTH quantization error and the
    quantized program's cross-shape variation, which (unlike f32's
    occasional 1 ulp) can be orders of magnitude above the rounding
    floor.  Non-finite quantized outputs count as infinite divergence.
    """
    buckets = sorted(set(int(b) for b in buckets))
    anchor = buckets[-1]
    rng = np.random.default_rng(seed)
    shape = tuple(int(d) for d in obs_shape)
    obs = rng.standard_normal((anchor,) + shape).astype(np.float32)
    ref = np.asarray(batch_fn(obs), np.float32)
    scale = float(max(np.max(np.abs(ref)), 1e-6))
    out: dict[int, float] = {}
    for b in buckets:
        worst = 0.0
        for _ in range(max(1, int(trials))):
            idx = rng.choice(anchor, size=b, replace=False)
            got = np.asarray(quant_fn(obs[idx]), np.float32)
            err = np.max(np.abs(got - ref[idx]))
            if not np.isfinite(err):
                worst = float("inf")
                break
            worst = max(worst, float(err) / scale)
        out[b] = worst
    return out


class DynamicBatcher:
    """Bounded-queue request coalescer over a batched predict callable."""

    def __init__(
        self,
        batch_fn: Callable[[np.ndarray], np.ndarray],
        obs_shape: Sequence[int],
        *,
        max_batch: int = 32,
        max_wait_ms: float = 4.0,
        max_queue: int = 256,
        telemetry=None,
        tracer=None,
        verify: bool = True,
        quant_fn: Callable[[np.ndarray], np.ndarray] | None = None,
        quant_bound: float | None = None,
        quant_label: str = "bf16",
    ):
        self.batch_fn = batch_fn
        self.obs_shape = tuple(int(d) for d in obs_shape)
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.obs = telemetry if telemetry is not None else NULL_TELEMETRY
        # optional per-process segment tracer (obs/tracing.py): the
        # server assigns its own after construction so batcher child
        # segments land in the SAME sampler deciding the request's fate
        self.tracer = tracer
        ladder = bucket_sizes(self.max_batch)
        if quant_fn is not None:
            if quant_bound is None:
                raise ValueError("quant_fn needs quant_bound (the documented "
                                 "per-bucket divergence bound)")
            if not verify and ladder[-1] >= 2:
                raise ValueError(
                    "quantized serving requires bucket verification — the "
                    "divergence contract chains to the VERIFIED f32 anchor")
        self.buckets_excluded: tuple[int, ...] = ()
        # verification applies to every coalescing ladder (anchor ≥ 2):
        # even a single-bucket ladder of 2 must prove slot-independence —
        # only the batch-1 baseline has nothing to coalesce
        if verify and ladder[-1] >= 2:
            # measured bit-consistency gate (see verify_stable_buckets);
            # the verification forwards also pre-compile every kept bucket,
            # so they count toward `recompiles` exactly once here
            t0 = time.perf_counter()
            stable, excluded = verify_stable_buckets(
                batch_fn, self.obs_shape, ladder)
            # one ledger entry for the verification pass (it IS the
            # ladder's compile cost); recompiles are counted per bucket
            # below, so count_recompiles=0 here
            self.obs.compile_event(
                "bucket_verify", time.perf_counter() - t0,
                count_recompiles=0, buckets=len(ladder), first_call=True)
            self.buckets = stable
            self.buckets_excluded = excluded
            for b in excluded:
                self.obs.counters.inc("buckets_excluded")
                self.obs.event("bucket_excluded", bucket=b)
        else:
            self.buckets = ladder
        self._q: queue.Queue[_Pending | None] = queue.Queue(
            maxsize=int(max_queue))
        self._closing = False
        # serializes the closing-flag check against close(): without it a
        # submit() preempted between check and enqueue could land in the
        # queue after close()'s final sweep and block its caller for the
        # whole request timeout (reachable via hot reload)
        self._close_lock = threading.Lock()
        self._buckets_seen: set[int] = set()
        if verify and ladder[-1] >= 2:
            # verification dispatched every ladder shape once — those ARE
            # the compiles; honest accounting means recompiles == ladder
            # length already, and dispatch never adds more
            for b in ladder:
                self._buckets_seen.add(b)
                self.obs.counters.inc("recompiles")
        # ------------------------------------------------ quantized path
        # opt-in accuracy-bounded fast path (docs/serving.md "Cold start &
        # quantized serving"): per-bucket divergence vs the f32 anchor is
        # MEASURED here; drifting buckets fall back to the f32 program at
        # the same shape (exact answers, evidence in the counters), and a
        # policy whose divergence exceeds the bound AT THE ANCHOR — pure
        # quantization error, no shape effects — is refused outright.
        self.quant_fn = quant_fn
        self.quant_bound = float(quant_bound) if quant_bound is not None \
            else None
        self.quant_label = str(quant_label)
        self.quant_divergence: dict[int, float] = {}
        self.quant_buckets: tuple[int, ...] = ()
        self.quant_buckets_excluded: tuple[int, ...] = ()
        self._quant_buckets: set[int] = set()
        if quant_fn is not None:
            t0 = time.perf_counter()
            div = measure_quant_divergence(
                quant_fn, batch_fn, self.obs_shape, self.buckets)
            self.quant_divergence = div
            anchor = self.buckets[-1]
            if not div[anchor] <= self.quant_bound:
                raise ValueError(
                    f"{self.quant_label} path exceeds the divergence bound "
                    f"at the anchor bucket {anchor}: measured "
                    f"{div[anchor]:.3g} > {self.quant_bound:g} — this "
                    "policy cannot serve quantized within the documented "
                    "accuracy bound; serve it f32"
                )
            keep = [b for b in self.buckets if div[b] <= self.quant_bound]
            dropped = [b for b in self.buckets if b not in keep]
            self.quant_buckets = tuple(keep)
            self.quant_buckets_excluded = tuple(dropped)
            self._quant_buckets = set(keep)
            for b in dropped:
                self.obs.counters.inc("quant_buckets_excluded")
                self.obs.event("quant_bucket_excluded", bucket=b,
                               dtype=self.quant_label,
                               divergence=round(div[b], 6),
                               bound=self.quant_bound)
            # the measurement compiled one quantized program per stable
            # bucket (and, when f32 verification did not run — the (1,)
            # ladder — the f32 anchor program too); count them so the
            # recompile budget stays honest and dispatch never adds more
            for b in self.buckets:
                self.obs.counters.inc("recompiles")
            if not self._buckets_seen:
                for b in self.buckets:
                    self._buckets_seen.add(b)
                    self.obs.counters.inc("recompiles")
            self.obs.compile_event(
                "quant_verify", time.perf_counter() - t0,
                count_recompiles=0, buckets=len(self.buckets),
                dtype=self.quant_label, first_call=True)
        self._worker = threading.Thread(
            target=self._run, name="batcher", daemon=True)
        self._worker.start()

    # ---------------------------------------------------------- intake

    def submit(self, obs, trace: str | None = None,
               span: str | None = None) -> _Pending:
        """Enqueue one observation; returns the pending slot to wait on.
        Sheds (:class:`BatcherSaturated`) when the queue is full.
        ``trace``: caller-assigned request id threaded through the
        recorder's shed/batch events (the server mints one per HTTP
        request); ``span``: the caller's request span id, parent of the
        lifecycle child segments."""
        if self._closing:
            raise BatcherClosed("batcher is draining — no new requests")
        arr = np.asarray(obs, np.float32)
        if arr.shape != self.obs_shape:
            raise ValueError(
                f"observation shape {arr.shape} != bundle obs_shape "
                f"{self.obs_shape}"
            )
        item = _Pending(arr, trace=trace, span=span)
        self.obs.counters.inc("requests_total")
        with self._close_lock:
            if self._closing:
                raise BatcherClosed("batcher is draining — no new requests")
            try:
                self._q.put_nowait(item)
            except queue.Full:
                self.obs.counters.inc("shed_total")
                self.obs.event("request_shed", queue_depth=self._q.qsize(),
                               **({"trace": trace} if trace else {}))
                raise BatcherSaturated(
                    f"request queue full ({self._q.maxsize}) — shedding "
                    "for backpressure"
                ) from None
        return item

    def predict(self, obs, timeout: float | None = 30.0,
                trace: str | None = None,
                span: str | None = None) -> np.ndarray:
        """submit + wait; raises the batch's error or TimeoutError."""
        item = self.submit(obs, trace=trace, span=span)
        if not item.event.wait(timeout):
            raise TimeoutError(f"no batch result within {timeout}s")
        if item.error is not None:
            raise item.error
        return item.result

    # ---------------------------------------------------------- worker

    def _bucket(self, n: int) -> int:
        # walk the STABLE ladder, not powers of two: an excluded interior
        # shape (e.g. B=4 failed verification) must be padded PAST, never
        # dispatched to — n ≤ max_batch = buckets[-1], so this always hits
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _run(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closing:
                    return
                continue
            if item is None:
                self._drain_remaining()
                return
            item.t_taken = time.perf_counter()
            batch = [item]
            deadline = item.t_taken + self.max_wait_s
            stop = False
            while len(batch) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    stop = True
                    break
                nxt.t_taken = time.perf_counter()
                batch.append(nxt)
            self._dispatch(batch)
            if stop:
                self._drain_remaining()
                return

    def _drain_remaining(self) -> None:
        """Service requests that slipped in BEHIND the close sentinel: a
        submit() racing close() can pass the ``_closing`` check and land
        after the None in the FIFO — returning at the sentinel would
        leave that caller blocked for its whole request timeout (the hot
        reload path closes a batcher that is still taking traffic)."""
        batch: list[_Pending] = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            item.t_taken = time.perf_counter()
            batch.append(item)
            if len(batch) >= self.max_batch:
                self._dispatch(batch)
                batch = []
        if batch:
            self._dispatch(batch)

    def _dispatch(self, batch: list[_Pending]) -> None:
        obs = self.obs
        n = len(batch)
        bucket = self._bucket(n)
        new_bucket = bucket not in self._buckets_seen
        if new_bucket:
            # one XLA compile per bucket shape — this counter staying
            # ≤ len(self.buckets) under mixed load is the test contract
            self._buckets_seen.add(bucket)
            obs.counters.inc("recompiles")
            obs.event("bucket_compile", bucket=bucket)
        arr = np.zeros((bucket,) + self.obs_shape, np.float32)
        t_dispatch = time.perf_counter()
        for i, item in enumerate(batch):
            arr[i] = item.obs
            # per-request lifecycle distributions (docs/observability.md
            # "Tails & traces"): time on the queue before a worker took
            # it, then time spent waiting for neighbors to coalesce
            if item.t_taken:
                obs.hists.observe("serve/queue_wait_s",
                                  item.t_taken - item.t_submit)
                obs.hists.observe("serve/coalesce_wait_s",
                                  t_dispatch - item.t_taken)
        obs.counters.gauge("queue_depth", self._q.qsize())
        obs.counters.gauge("batch_size_last", n)
        obs.counters.gauge("bucket_last", bucket)
        # thread-safe primitives only (note/counters): during a hot
        # reload the OLD batcher drains while the NEW one serves, and two
        # workers sharing the Telemetry would corrupt its span stack —
        # obs.phase is single-writer machinery.  The heartbeat still
        # shows "predict" as the last phase under load, and the timing
        # lands in counters (which is all the serving summary reads).
        obs.note("predict")
        # quantized fast path for buckets measured within the divergence
        # bound; excluded buckets dispatch the f32 program at the SAME
        # shape — a drifting bucket degrades to exact, never to wrong
        use_quant = self.quant_fn is not None and bucket in self._quant_buckets
        fn = self.quant_fn if use_quant else self.batch_fn
        t_predict = time.perf_counter()
        try:
            out = fn(arr)
            err = None
        except Exception as e:  # noqa: BLE001 — propagated to every waiter
            # typed so the server can answer 500 (server fault), never
            # mistake it for a caller's 400-grade ValueError
            err = BatchError(f"batched predict failed: {e!r}")
            err.__cause__ = e
            obs.counters.inc("batch_errors_total")
            obs.event("batch_error", error=repr(e)[:200])
        dt = time.perf_counter() - t_predict
        if new_bucket and err is None:
            # a lazily-compiled bucket's first call is compile-dominated:
            # its wall seconds are the closest thing to a compile time
            # the dispatch path can observe (count_recompiles=0 — the
            # seen-check above already counted it).  compile_event uses
            # thread-safe primitives only, per the worker-thread contract
            obs.compile_event(f"bucket_{bucket}", dt, count_recompiles=0,
                              bucket=bucket, first_call=True)
        obs.counters.inc("predict_time_s_total", dt)
        if use_quant:
            obs.counters.inc("quant_batches_total")
            obs.counters.inc("quant_requests_total", n)
        # the compute cost every coalesced request shared, as a
        # DISTRIBUTION (n-weighted: per request, not per batch) — a
        # last-write gauge here would keep exactly the sample the tail
        # is not in (esguard R12 gauge-shaped-latency)
        obs.hists.observe("serve/compute_s", dt, n=n)
        obs.counters.inc("batches_total")
        obs.counters.inc("batched_requests_total", n)
        traces = [item.trace for item in batch if item.trace]
        if traces:
            # causal record: which requests rode this dispatch (the
            # ring is bounded, so high-RPS churn evicts, not grows)
            obs.event("batch_dispatch", bucket=bucket, n=n,
                      dur_ms=round(dt * 1e3, 3), traces=traces)
        tracer = self.tracer
        # one wall/mono pair: every segment of this dispatch rebases its
        # perf_counter mark onto the same wall epoch (cross-process
        # assembly aligns on wall `ts`; see obs/tracing.py)
        wall = time.time() if tracer is not None else 0.0
        mono = time.perf_counter()
        if tracer is not None and traces:
            # per-dispatch `batch` span linking the member request ids —
            # bypasses the tail sampler (record): dispatch volume is
            # already bounded by construction, and the span must survive
            # for WHICHEVER member the sampler ends up keeping
            tracer.record(make_segment(
                traces[0], tracer.span_id(), None, tracer.proc, "batch",
                t_dispatch, dt, attrs={"bucket": bucket, "n": n,
                                       "traces": traces},
                ts=wall - (mono - t_dispatch)))
        if err is None:
            # own the results before crossing threads: np.asarray on a jax
            # output is a ZERO-COPY view of the XLA buffer, and waiter
            # threads read it milliseconds later — after the worker has
            # dispatched more batches into the same allocator.  Observed
            # (1-ulp flaky rows under load) before this copy; the copy is
            # (bucket, action_dim) floats, noise next to the forward pass.
            out = np.array(out, np.float32, copy=True)
        t_done = time.perf_counter()
        for i, item in enumerate(batch):
            if err is None:
                item.result = out[i]
            else:
                item.error = err
            if tracer is not None and item.trace and item.span:
                # lifecycle children under the server's request span,
                # recorded BEFORE event.set() so they are buffered by the
                # time the handler thread applies the tail verdict
                for nm, t0s, ds in (
                        ("queue_wait", item.t_submit,
                         item.t_taken - item.t_submit),
                        ("coalesce", item.t_taken,
                         t_dispatch - item.t_taken),
                        ("compute", t_predict, dt)):
                    tracer.add(make_segment(
                        item.trace, tracer.span_id(), item.span,
                        tracer.proc, nm, t0s, ds,
                        ts=wall - (mono - t0s)))
            # full in-batcher request latency (submit → result ready):
            # the quantity the server's tail SLO is about, and the one
            # the quantile-honesty test reconciles against loadgen;
            # the exemplar ties the bucket back to an assemblable trace
            obs.hists.observe("serve/request_s", t_done - item.t_submit,
                              exemplar=item.trace)
            item.event.set()

    # ----------------------------------------------------------- drain

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop intake; with ``drain`` finish every queued request, then
        join the worker.  Without ``drain`` pending requests get
        :class:`BatcherClosed` set as their error."""
        with self._close_lock:
            if self._closing:
                already = True
            else:
                already = False
                self._closing = True
        if already:
            self._worker.join(timeout)
            return
        if not drain:
            # fail queued waiters fast instead of leaving them blocked
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    item.error = BatcherClosed("batcher closed without drain")
                    item.event.set()
        try:
            self._q.put_nowait(None)  # wake + stop the worker
        except queue.Full:
            pass  # worker is draining a full queue; the _closing flag stops it
        self._worker.join(timeout)
        # a submit() that raced close() may have enqueued after the worker
        # exited — fail those waiters loudly instead of leaving them to
        # time out against a dead queue
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item.error = BatcherClosed("batcher closed mid-submit")
                item.event.set()

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        c = self.obs.counters
        batches = c.get("batches_total")
        served = c.get("batched_requests_total")
        out = {
            "queue_depth": self._q.qsize(),
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            "buckets_excluded": list(self.buckets_excluded),
            "buckets_compiled": sorted(self._buckets_seen),
            "requests_total": int(c.get("requests_total")),
            "batches_total": int(batches),
            "shed_total": int(c.get("shed_total")),
            "recompiles": int(c.get("recompiles")),
            "mean_batch": round(served / batches, 3) if batches else None,
        }
        if self.quant_fn is not None:
            out["quant"] = {
                "dtype": self.quant_label,
                "bound": self.quant_bound,
                "buckets": list(self.quant_buckets),
                "excluded": list(self.quant_buckets_excluded),
                "divergence": {str(b): round(v, 6)
                               for b, v in self.quant_divergence.items()},
                "batches_total": int(c.get("quant_batches_total")),
            }
        hists = self.obs.hists
        lat = {}
        for q, key in ((0.5, "p50"), (0.99, "p99")):
            v = hists.quantile("serve/request_s", q)
            if v is not None:
                lat[key] = round(v * 1e3, 3)
        if lat:
            out["request_ms"] = lat
        return out
