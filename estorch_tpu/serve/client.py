"""Minimal stdlib client for the policy server (serve/server.py).

One persistent keep-alive connection per instance — NOT thread-safe by
design (``http.client`` connections aren't); give each thread its own
client.  For load generation use serve/loadgen.py, whose selector-based
engine keeps many requests in flight from one thread.
"""

from __future__ import annotations

import http.client
import json


class ServeError(RuntimeError):
    """Non-2xx server answer; ``.status`` and ``.payload`` carry it."""

    def __init__(self, status: int, payload: dict):
        super().__init__(f"server answered {status}: {payload}")
        self.status = status
        self.payload = payload


class ServeClient:
    """``ServeClient("127.0.0.1:8321").predict([0.1, 0.2, 0.3])``."""

    def __init__(self, address: str, timeout_s: float = 30.0):
        if "://" in address:
            address = address.split("://", 1)[1]
        host, _, port = address.rstrip("/").partition(":")
        self.host = host
        self.port = int(port or 80)
        self.timeout_s = float(timeout_s)
        self._conn: http.client.HTTPConnection | None = None

    # ------------------------------------------------------------ plumbing

    def _request(self, method: str, path: str, payload: dict | None = None):
        body = json.dumps(payload).encode() if payload is not None else None
        headers = {"Content-Type": "application/json"} if body else {}
        # transparent stale-connection retry for GETs only: a POST whose
        # connection died may ALREADY have been executed server-side
        # (predict counted, reload performed) — silently replaying a
        # non-idempotent request double-applies it, so POST failures
        # surface to the caller, who owns the retry decision
        retriable = method == "GET"
        for attempt in (0, 1):
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s)
            try:
                self._conn.request(method, path, body, headers)
                resp = self._conn.getresponse()
                data = resp.read()
                break
            except (http.client.HTTPException, OSError):
                self.close()
                if attempt or not retriable:
                    raise
        try:
            parsed = json.loads(data) if data else {}
        except ValueError:
            parsed = {"raw": data.decode(errors="replace")}
        if resp.status >= 300:
            raise ServeError(resp.status, parsed)
        return parsed

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- surface

    def predict(self, obs) -> list:
        """One observation → the policy output as a (nested) list.  The
        JSON float round trip is exact (repr shortest-round-trip), so
        the listed values are bit-identical to the server's float32
        outputs."""
        if hasattr(obs, "tolist"):
            obs = obs.tolist()
        return self._request("POST", "/predict", {"obs": obs})["action"]

    def health(self) -> dict:
        return self._request("GET", "/healthz")

    def stats(self) -> dict:
        return self._request("GET", "/stats")

    def reload(self, bundle_path: str) -> dict:
        return self._request("POST", "/reload", {"path": bundle_path})
