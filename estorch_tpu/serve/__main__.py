"""serve CLI: ``python -m estorch_tpu.serve --bundle <dir>``.

Platform policy happens HERE, before any jax-importing module loads:
``--cpu-devices N`` pins the CPU backend with N virtual devices — serve
on the same host compute configuration as the exporting run and the
bit-exactness contract holds across the process boundary
(docs/serving.md).

``--supervised`` wraps the server in the PR-3 watchdog
(resilience/supervisor.py): heartbeat-staleness + exit-status restarts
with exponential backoff; SIGTERM to the supervisor forwards to the
child, which drains and exits cleanly.

Exit codes: 0 clean drain; 1 drain left work behind / supervision gave
up; 2 bad bundle or arguments.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.serve",
        description="serve a policy bundle over HTTP (docs/serving.md); "
                    "`route --fleet fleet.json` runs the fleet front "
                    "router instead (docs/serving.md, 'Fleet')")
    p.add_argument("--bundle", required=True, metavar="DIR",
                   help="bundle directory written by export_bundle")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8321,
                   help="0 picks an ephemeral port (see --port-file)")
    p.add_argument("--max-batch", type=int, default=32,
                   help="bucket ladder top (power of two); 1 = the "
                        "batch-size-1 baseline")
    p.add_argument("--max-wait-ms", type=float, default=4.0,
                   help="batching window from the first queued request")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission-control queue bound (full => 503)")
    p.add_argument("--cpu-devices", type=int, default=0, metavar="N",
                   help="force the CPU backend with N virtual devices "
                        "BEFORE jax init — match the exporting run for "
                        "cross-process bit-parity (0 = leave platform "
                        "alone)")
    p.add_argument("--warm", action="store_true",
                   help="pre-compile every bucket before READY (flat "
                        "first-request latency; counts toward the "
                        "recompiles counter exactly like lazy compiles)")
    p.add_argument("--no-warm", action="store_true",
                   help="ignore warmth packed in the bundle (serve/warm.py)"
                        " — the cold-start A/B's control leg")
    p.add_argument("--dtype", choices=("f32", "bf16"), default="f32",
                   help="serving compute dtype; bf16 is the quantized "
                        "fast path — refused (exit 2 / 409) unless the "
                        "bundle opted in at export and its measured "
                        "divergence stays inside the documented bound "
                        "(docs/serving.md)")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="atomically write {host,port,pid} JSON once bound")
    p.add_argument("--run-dir", default=None, metavar="DIR",
                   help="per-process observability dir: sampled trace "
                        "segments flush to <DIR>/traces.jsonl "
                        "(docs/observability.md 'Distributed tracing')")
    p.add_argument("--beat-interval", type=float, default=2.0,
                   help="idle heartbeat period (ESTORCH_OBS_HEARTBEAT)")
    p.add_argument("--supervised", action="store_true",
                   help="run under the resilience watchdog (heartbeat "
                        "staleness + crash restarts)")
    p.add_argument("--supervise-root", default="serve_run", metavar="DIR",
                   help="supervision state dir (heartbeat, manifest)")
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--stale-after-s", type=float, default=30.0)
    p.add_argument("--startup-grace-s", type=float, default=120.0)
    return p


def main(argv=None) -> int:
    import time

    t0 = time.monotonic()  # startup_s covers the jax import + load
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "route":
        # the fleet front door (docs/serving.md "Fleet"): router +
        # optional fleet supervisor — deliberately jax-free, so the
        # dispatch happens before the bundle-serving machinery loads
        from .router import main as route_main

        return route_main(argv[1:])
    args = build_parser().parse_args(argv)
    args._t0_monotonic = t0
    # config validation BEFORE anything heavy (and before --supervised
    # forks): a bad --max-batch must be exit 2 with one line, not a
    # traceback — or worse, a supervised child crash-looping through
    # max_restarts on a typo
    from .batcher import bucket_sizes

    try:
        bucket_sizes(args.max_batch)
    except ValueError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2
    if args.cpu_devices > 0:
        from ..utils import force_cpu_backend

        force_cpu_backend(args.cpu_devices)
    from .bundle import BundleError
    from .server import run_server, run_supervised

    try:
        if args.supervised:
            return run_supervised(args, argv)
        return run_server(args)
    except BundleError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
