"""Load generator for the policy server: open/closed-loop traffic with
throughput and latency percentiles.

Engine design: ONE thread drives N persistent connections through a
``selectors`` loop, each connection holding at most one request in
flight.  On a GIL'd host this measures the server honestly — a
thread-per-connection client spends more time context-switching than
talking, and (measured) *lowers* observed server throughput as
concurrency rises.  Closed loop: every connection fires its next request
the moment its response lands — offered load tracks capacity, the right
mode for "how fast CAN it go" A/Bs.  Open loop: requests fire on a fixed
schedule (``target_rps``) regardless of completions — queueing delay
shows up in the latencies, the right mode for "what happens at X rps".

Deliberately stdlib-only and importable without the package (run as
``python estorch_tpu/serve/loadgen.py``) so the run_lint smoke and a
wedged-jax host can still drive/load-test a server.
"""

from __future__ import annotations

import argparse
import json
import selectors
import socket
import sys
import time


def _percentile(sorted_xs: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted sample (q in [0, 1])."""
    if not sorted_xs:
        return float("nan")
    i = min(len(sorted_xs) - 1, max(0, int(q * len(sorted_xs))))
    return sorted_xs[i]


class _Conn:
    __slots__ = ("sock", "buf", "sent_at", "req_index", "busy")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""
        self.sent_at = 0.0
        self.req_index = -1
        self.busy = False


def _parse_responses(conn: _Conn):
    """Yield (status, body bytes, trace id) for each complete HTTP
    response in the buffer; leaves partial data buffered.  The trace id
    is the server's ``X-Trace-Id`` response header ("" when absent) —
    the join key between a latency row and the assembled distributed
    trace (``obs trace --fleet`` / ``obs slow``)."""
    while True:
        head_end = conn.buf.find(b"\r\n\r\n")
        if head_end < 0:
            return
        head = conn.buf[:head_end]
        status = int(head.split(b" ", 2)[1])
        clen = 0
        trace = ""
        for line in head.split(b"\r\n")[1:]:
            if line[:15].lower() == b"content-length:":
                clen = int(line[15:])
            elif line[:11].lower() == b"x-trace-id:":
                trace = line[11:].strip().decode("ascii", "replace")
        total = head_end + 4 + clen
        if len(conn.buf) < total:
            return
        body = conn.buf[head_end + 4:total]
        conn.buf = conn.buf[total:]
        yield status, body, trace


def run_load(
    address: str,
    *,
    mode: str = "closed",
    conns: int = 8,
    duration_s: float = 3.0,
    total: int | None = None,
    target_rps: float | None = None,
    obs: list | None = None,
    obs_list: list | None = None,
    collect_responses: bool = False,
    collect_latencies: bool = False,
    timeout_s: float = 60.0,
) -> dict:
    """Drive ``/predict`` traffic; returns the measurement dict.

    ``obs_list`` assigns observation i to request i (requests are issued
    in index order; with ``collect_responses`` the returned
    ``responses[i]`` is request i's parsed body — the bit-exactness
    check's plumbing).  ``total`` stops after exactly that many requests
    (default: run for ``duration_s``).  ``mode="open"`` needs
    ``target_rps``.  ``collect_latencies`` returns the raw per-request
    latency list (``latencies_s``, completion order) — the offline
    samples the ``obs regress --tail`` gate and the quantile-honesty
    test consume.
    """
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be closed|open, got {mode!r}")
    if mode == "open" and not target_rps:
        raise ValueError("open-loop load needs target_rps")
    if obs_list is None:
        obs_list = [obs if obs is not None else [0.0]]
    bodies = [json.dumps({"obs": o}).encode() for o in obs_list]
    reqs = [
        b"POST /predict HTTP/1.1\r\nHost: x\r\nContent-Type: application/json"
        b"\r\nContent-Length: " + str(len(b)).encode() + b"\r\n\r\n" + b
        for b in bodies
    ]

    if "://" in address:
        address = address.split("://", 1)[1]
    host, _, port = address.rstrip("/").partition(":")
    addr = (host, int(port))

    sel = selectors.DefaultSelector()
    pool: list[_Conn] = []
    for _ in range(int(conns)):
        s = socket.create_connection(addr, timeout=timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setblocking(False)
        c = _Conn(s)
        sel.register(s, selectors.EVENT_READ, c)
        pool.append(c)

    import collections

    latencies: list[float] = []
    trace_ids: list[str] = []
    responses: list | None = [None] * len(obs_list) if collect_responses else None
    issued = completed = errors = shed = scheduled = 0
    t0 = time.perf_counter()
    deadline = t0 + float(duration_s)
    interval = 1.0 / target_rps if target_rps else 0.0
    next_send = t0
    # open loop: the SCHEDULE is authoritative — ticks accumulate here
    # even while every connection is busy, and a request's latency is
    # measured from its scheduled time, so queueing delay above capacity
    # shows up in the percentiles instead of being coordinated away
    backlog: collections.deque[float] = collections.deque()

    def want_more(now: float) -> bool:
        if total is not None:
            return scheduled < total if mode == "open" else issued < total
        return now < deadline

    def tick_schedule(now: float) -> None:
        nonlocal next_send, scheduled
        if mode != "open":
            return
        while next_send <= now and want_more(now):
            backlog.append(next_send)
            scheduled += 1
            next_send += interval

    def retire(c: _Conn) -> None:
        nonlocal completed, errors
        if c.busy:
            errors += 1
            completed += 1
            c.busy = False
        sel.unregister(c.sock)
        c.sock.close()
        pool.remove(c)

    def send_on(c: _Conn, sent_at: float) -> bool:
        """Issue the next request on ``c`` (``sent_at``: the wall time
        latency is measured from — the actual send for closed loop, the
        SCHEDULED time for open loop).  A send failure (server closed
        the connection mid-measurement) retires the connection and
        counts the request as an error instead of blowing up the whole
        measurement."""
        nonlocal issued, errors, completed
        c.req_index = issued
        c.sent_at = sent_at
        c.busy = True
        issued += 1
        try:
            c.sock.sendall(reqs[c.req_index % len(reqs)])
        except OSError:
            retire(c)
            return False
        return True

    def feed_idle(now: float) -> None:
        tick_schedule(now)
        for c in [c for c in pool if not c.busy]:
            if mode == "open":
                if not backlog:
                    break
                send_on(c, backlog.popleft())
            else:
                if not want_more(time.perf_counter()):
                    break
                send_on(c, time.perf_counter())

    feed_idle(t0)

    hard_stop = t0 + float(duration_s) + timeout_s
    while (completed < issued or backlog
           or want_more(time.perf_counter())):
        now = time.perf_counter()
        if now > hard_stop:
            errors += issued - completed
            break
        feed_idle(now)
        wait = 0.05
        if mode == "open" and want_more(now) and not backlog:
            wait = min(wait, max(0.0, next_send - now))
        for key, _ in sel.select(timeout=wait):
            c: _Conn = key.data
            try:
                chunk = c.sock.recv(1 << 16)
            except BlockingIOError:
                continue
            except OSError:
                chunk = b""
            if not chunk:
                # server closed the connection (drain) — count any
                # outstanding request on it as an error and retire it
                retire(c)
                if not pool:
                    break
                continue
            c.buf += chunk
            for status, body, trace in _parse_responses(c):
                completed += 1
                latencies.append(time.perf_counter() - c.sent_at)
                trace_ids.append(trace)
                if status == 503:
                    shed += 1
                elif status != 200:
                    errors += 1
                if responses is not None and 0 <= c.req_index < len(responses):
                    try:
                        responses[c.req_index] = json.loads(body)
                    except ValueError:
                        responses[c.req_index] = None
                c.busy = False
                now = time.perf_counter()
                if mode == "open":
                    tick_schedule(now)
                    if backlog:
                        send_on(c, backlog.popleft())
                elif want_more(now):
                    send_on(c, now)
        if not pool:
            errors += issued - completed
            break

    wall = time.perf_counter() - t0
    for c in pool:
        sel.unregister(c.sock)
        c.sock.close()
    sel.close()
    lat_sorted = sorted(latencies)
    out = {
        "mode": mode,
        "conns": int(conns),
        "requests": completed,
        "errors": errors,
        "shed": shed,
        "duration_s": round(wall, 4),
        "throughput_rps": round(completed / wall, 2) if wall > 0 else 0.0,
        "latency_ms": {
            "p50": round(_percentile(lat_sorted, 0.50) * 1e3, 3),
            "p95": round(_percentile(lat_sorted, 0.95) * 1e3, 3),
            "p99": round(_percentile(lat_sorted, 0.99) * 1e3, 3),
            "mean": round(sum(lat_sorted) / len(lat_sorted) * 1e3, 3)
            if lat_sorted else float("nan"),
            "max": round(lat_sorted[-1] * 1e3, 3) if lat_sorted else
            float("nan"),
        },
    }
    if target_rps:
        out["target_rps"] = float(target_rps)
    if responses is not None:
        out["responses"] = responses
    if collect_latencies:
        out["latencies_s"] = latencies
        # same completion order as latencies_s: trace_ids[i] is the
        # server's X-Trace-Id for the request latencies_s[i] measured
        out["trace_ids"] = trace_ids
    return out


def coldstart_probe(
    address: str,
    *,
    total: int = 100,
    conns: int = 4,
    obs: list | None = None,
    timeout_s: float = 180.0,
) -> dict:
    """Cold-start measurement against a just-started server: the FIRST
    request is fired alone on one connection (so any JIT pause lands on
    exactly one measured sample — ``ttfr_s``), then the remainder of the
    first ``total`` requests run concurrently for the early-tail
    percentiles (``first_p99_ms``) — the two facts
    ``bench.py --coldstart`` gates (docs/serving.md "Cold start &
    quantized serving").  The caller measures process spawn → ready
    separately; this probe owns ready → first answers."""
    first = run_load(address, conns=1, total=1, duration_s=timeout_s,
                     obs=obs, collect_latencies=True, timeout_s=timeout_s)
    rest = {"errors": 0, "shed": 0, "latencies_s": []}
    if total > 1:
        rest = run_load(address, conns=conns, total=int(total) - 1,
                        duration_s=timeout_s, obs=obs,
                        collect_latencies=True, timeout_s=timeout_s)
    lats = list(first.get("latencies_s", [])) + list(
        rest.get("latencies_s", []))
    lat_sorted = sorted(lats)
    return {
        "ttfr_s": round(first["latencies_s"][0], 4)
        if first.get("latencies_s") else None,
        "first_requests": len(lats),
        "first_p50_ms": round(_percentile(lat_sorted, 0.50) * 1e3, 3),
        "first_p99_ms": round(_percentile(lat_sorted, 0.99) * 1e3, 3),
        "errors": first["errors"] + rest["errors"],
        "shed": first.get("shed", 0) + rest.get("shed", 0),
        "latencies_s": lats,
        "trace_ids": list(first.get("trace_ids", [])) + list(
            rest.get("trace_ids", [])),
    }


def capacity_sweep(
    address: str,
    *,
    slo_ms: float = 50.0,
    rps_ladder: list[float] | None = None,
    start_rps: float = 25.0,
    growth: float = 2.0,
    max_rungs: int = 8,
    rung_duration_s: float = 2.0,
    conns: int = 16,
    obs: list | None = None,
    quantile: float = 0.99,
    max_error_frac: float = 0.0,
    timeout_s: float = 60.0,
) -> dict:
    """The ROADMAP capacity model: an OPEN-LOOP offered-load ladder —
    each rung fires requests on a fixed schedule regardless of
    completions, with latency measured from the SCHEDULED send time
    (``run_load``'s schedule-authoritative rule), so queueing delay past
    saturation lands in the percentiles instead of being coordinated
    away.  Reports per-rung rows and ``max_rps_at_slo``: the highest
    offered rate whose ``quantile`` latency stayed <= ``slo_ms`` with
    error+shed fraction <= ``max_error_frac``.

    ``rps_ladder`` pins the rungs explicitly; otherwise a geometric
    ladder (``start_rps`` × ``growth``^k) runs until the SLO breaks or
    ``max_rungs`` is exhausted (the early stop keeps a saturated server
    from being hammered through rungs that can only fail).
    """
    ladder = ([float(r) for r in rps_ladder] if rps_ladder
              else [start_rps * (growth ** k) for k in range(max_rungs)])
    qkey = f"p{quantile * 100:g}"
    rungs: list[dict] = []
    max_ok: float | None = None
    for rps in ladder:
        res = run_load(address, mode="open", target_rps=rps, conns=conns,
                       duration_s=rung_duration_s, obs=obs,
                       collect_latencies=True, timeout_s=timeout_s)
        lat_sorted = sorted(res.pop("latencies_s", []))
        q_ms = _percentile(lat_sorted, quantile) * 1e3
        bad = res["errors"] + res["shed"]
        bad_frac = bad / res["requests"] if res["requests"] else 1.0
        ok = (bool(lat_sorted) and q_ms <= slo_ms
              and bad_frac <= max_error_frac)
        rungs.append({
            "offered_rps": rps,
            "achieved_rps": res["throughput_rps"],
            qkey + "_ms": round(q_ms, 3),
            "errors": res["errors"],
            "shed": res["shed"],
            "requests": res["requests"],
            "ok": ok,
        })
        if ok:
            max_ok = rps
        elif rps_ladder is None:
            break  # saturated: further geometric rungs can only fail
    return {
        "slo_ms": float(slo_ms),
        "quantile": qkey,
        "rungs": rungs,
        "max_rps_at_slo": max_ok,
        "saturated": any(not r["ok"] for r in rungs),
    }


CAPACITY_SCHEMA = 1


def write_capacity_artifact(sweep: dict, path: str, *,
                            bundle: str | None = None,
                            platform: str | None = None) -> dict:
    """Persist a :func:`capacity_sweep` result as the VERSIONED capacity
    model the autoscaler consumes (obs/agg/autoscale.py owns the
    validator — the two keep ``schema`` in lockstep).

    ``bundle`` stamps identity from the bundle's MANIFEST.json (arrays
    sha256, version, warm platform — read jax-free): the autoscaler
    refuses a model whose bundle/platform mismatches the fleet it is
    about to scale, naming both sides.  ``platform`` overrides the
    manifest's warm platform (a cold-exported bundle has none)."""
    import os

    art = {
        "schema": CAPACITY_SCHEMA,
        "kind": "capacity",
        "created_ts": time.time(),
        "slo_ms": sweep["slo_ms"],
        "quantile": sweep["quantile"],
        "max_rps_at_slo": sweep["max_rps_at_slo"],
        "saturated": sweep["saturated"],
        "rungs": sweep["rungs"],
        "bundle_sha": None,
        "bundle_version": None,
        "platform": platform,
    }
    if bundle:
        try:
            with open(os.path.join(bundle, "MANIFEST.json")) as f:
                man = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError(
                f"{bundle}: unreadable bundle MANIFEST.json: {e}") from e
        art["bundle_version"] = man.get("version")
        art["bundle_sha"] = (man.get("sha256") or {}).get("arrays.npz")
        if platform is None:
            art["platform"] = (man.get("warm") or {}).get("platform")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(art, f, indent=1)
    os.replace(tmp, path)
    return art


def write_latency_rows(latencies_s: list, path: str,
                       endpoint: str = "/predict",
                       trace_ids: list | None = None) -> str:
    """Per-request latency rows as JSONL (``{"endpoint", "latency_s"}``)
    — the measurement file shape ``obs regress --tail`` groups by
    endpoint.  When ``trace_ids`` is given (same completion order as
    ``latencies_s``), each row that has one gains a ``trace_id`` column:
    the server's ``X-Trace-Id``, so a tail outlier in the measurement
    file can be looked up as an assembled distributed trace
    (``obs trace --fleet`` / ``obs slow --store``).  Atomic (tmp +
    rename), like every other artifact."""
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for i, v in enumerate(latencies_s):
            row = {"endpoint": endpoint, "latency_s": float(v)}
            if trace_ids is not None and i < len(trace_ids) and trace_ids[i]:
                row["trace_id"] = str(trace_ids[i])
            f.write(json.dumps(row) + "\n")
    os.replace(tmp, path)
    return path


# ------------------------------------------------------------------ smoke

def _selfcheck() -> int:
    """Self-contained plumbing gate for run_lint.sh: spin a trivial
    stdlib echo server in-process, drive both loop modes against it,
    and validate the measurement schema.  No jax, no numpy, ~1s."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Echo(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        served = 0  # class-level: stamps each response's X-Trace-Id

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            data = json.loads(self.rfile.read(n))
            body = json.dumps({"action": data["obs"]}).encode()
            Echo.served += 1
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Trace-Id", f"t-{Echo.served:06d}")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Echo)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    addr = f"127.0.0.1:{srv.server_address[1]}"
    problems = []
    try:
        obs_list = [[float(i), 1.0] for i in range(16)]
        closed = run_load(addr, conns=4, total=16, duration_s=5.0,
                          obs_list=obs_list, collect_responses=True,
                          collect_latencies=True)
        if closed["requests"] != 16 or closed["errors"]:
            problems.append(f"closed loop lost requests: {closed}")
        if len(closed.get("latencies_s", [])) != 16:
            problems.append("per-request latencies not collected")
        tids = closed.get("trace_ids", [])
        if len(tids) != 16 or len(set(tids)) != 16 or not all(tids):
            problems.append(f"X-Trace-Id response headers not captured "
                            f"per request: {tids}")
        import os
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            rows_path = write_latency_rows(
                closed["latencies_s"], os.path.join(td, "lat.jsonl"),
                trace_ids=tids)
            with open(rows_path) as f:
                rows = [json.loads(line) for line in f]
            if ([r.get("trace_id") for r in rows] != tids
                    or any("latency_s" not in r for r in rows)):
                problems.append("latency rows lost the trace_id column")
        got = [r and r["action"] for r in closed["responses"]]
        if got != obs_list:
            problems.append("responses not matched to request indices")
        lat = closed["latency_ms"]
        if not (lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]):
            problems.append(f"percentiles not monotone: {lat}")
        open_ = run_load(addr, mode="open", target_rps=200, conns=4,
                         duration_s=0.5)
        if open_["requests"] == 0 or open_["errors"]:
            problems.append(f"open loop failed: {open_}")
        if not (0.3 * 200 * 0.5 < open_["requests"] <= 1.7 * 200 * 0.5):
            problems.append(
                f"open loop missed its schedule: {open_['requests']} "
                "requests for target 200 rps x 0.5s")
        # capacity ladder: the echo server answers in microseconds, so a
        # generous SLO must pass every rung and report the top one
        sweep = capacity_sweep(addr, slo_ms=1000.0,
                               rps_ladder=[50, 100], conns=4,
                               rung_duration_s=0.4)
        if sweep["max_rps_at_slo"] != 100.0 or sweep["saturated"]:
            problems.append(f"capacity sweep missed the trivially-"
                            f"passing ladder: {sweep}")
        if [r["offered_rps"] for r in sweep["rungs"]] != [50.0, 100.0]:
            problems.append(f"capacity rungs wrong: {sweep['rungs']}")
        # an impossible SLO must read as saturation, not success
        tight = capacity_sweep(addr, slo_ms=1e-6, rps_ladder=[50],
                               conns=4, rung_duration_s=0.3)
        if tight["max_rps_at_slo"] is not None or not tight["saturated"]:
            problems.append(f"impossible SLO not flagged: {tight}")
    finally:
        srv.shutdown()
        srv.server_close()
    for p in problems:
        print(f"loadgen selfcheck: {p}", file=sys.stderr)
    if not problems:
        print("loadgen selfcheck: OK (closed+open loop, percentiles, "
              "response indexing, trace-id capture, capacity sweep)")
    return 1 if problems else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="drive /predict load against a policy server")
    p.add_argument("--address", help="host:port of a running server")
    p.add_argument("--mode", choices=("closed", "open"), default="closed")
    p.add_argument("--conns", type=int, default=8)
    p.add_argument("--duration", type=float, default=3.0)
    p.add_argument("--target-rps", type=float, default=None)
    p.add_argument("--obs", default=None,
                   help="JSON observation, e.g. '[0.1, 0.2, 0.3]'")
    p.add_argument("--coldstart", type=int, default=None, metavar="N",
                   help="cold-start probe instead of a load run: first "
                        "request alone (time-to-first-response), then the "
                        "first N requests' p50/p99")
    p.add_argument("--capacity-sweep", action="store_true",
                   help="open-loop offered-load ladder: max sustainable "
                        "RPS at the --slo-ms p99 SLO (schedule-"
                        "authoritative latencies, so saturation is "
                        "honest)")
    p.add_argument("--slo-ms", type=float, default=50.0,
                   help="p99 latency SLO for --capacity-sweep")
    p.add_argument("--rps-ladder", default=None, metavar="R1,R2,...",
                   help="explicit offered-load rungs (default: geometric "
                        "from --start-rps)")
    p.add_argument("--start-rps", type=float, default=25.0)
    p.add_argument("--rung-duration", type=float, default=2.0)
    p.add_argument("--out", default=None, metavar="PATH",
                   help="persist the --capacity-sweep result as the "
                        "versioned capacity.json artifact the "
                        "autoscaler consumes")
    p.add_argument("--bundle", default=None, metavar="DIR",
                   help="stamp --out with this bundle's identity "
                        "(MANIFEST.json sha256/version/warm platform)")
    p.add_argument("--platform", default=None,
                   help="platform stamp for --out (overrides the "
                        "bundle manifest's warm platform)")
    p.add_argument("--latencies-out", default=None, metavar="PATH",
                   help="also write per-request latency rows as JSONL "
                        "({'endpoint', 'latency_s', 'trace_id'}) — the "
                        "obs regress --tail measurement format; trace_id "
                        "joins a row to its assembled distributed trace")
    p.add_argument("--selfcheck", action="store_true",
                   help="validate the loadgen itself against an "
                        "in-process echo server (CI gate)")
    args = p.parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    if not args.address:
        p.error("--address is required (or --selfcheck)")
    if args.capacity_sweep:
        ladder = ([float(x) for x in args.rps_ladder.split(",")]
                  if args.rps_ladder else None)
        res = capacity_sweep(
            args.address, slo_ms=args.slo_ms, rps_ladder=ladder,
            start_rps=args.start_rps, rung_duration_s=args.rung_duration,
            conns=args.conns,
            obs=json.loads(args.obs) if args.obs else None)
        if args.out:
            try:
                write_capacity_artifact(res, args.out,
                                        bundle=args.bundle,
                                        platform=args.platform)
            except ValueError as e:
                print(f"loadgen: {e}", file=sys.stderr)
                return 2
            res["artifact"] = args.out
        print(json.dumps(res))
        return 0
    if args.coldstart:
        res = coldstart_probe(
            args.address, total=args.coldstart, conns=args.conns,
            obs=json.loads(args.obs) if args.obs else None)
        lats = res.pop("latencies_s")
        traces = res.pop("trace_ids", None)
        if args.latencies_out:
            write_latency_rows(lats, args.latencies_out, trace_ids=traces)
            res["latencies_out"] = args.latencies_out
        print(json.dumps(res))
        return 0
    res = run_load(
        args.address, mode=args.mode, conns=args.conns,
        duration_s=args.duration, target_rps=args.target_rps,
        obs=json.loads(args.obs) if args.obs else None,
        collect_latencies=bool(args.latencies_out),
    )
    if args.latencies_out:
        write_latency_rows(res.pop("latencies_s"), args.latencies_out,
                           trace_ids=res.pop("trace_ids", None))
        res["latencies_out"] = args.latencies_out
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
