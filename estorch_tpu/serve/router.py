"""Front router for a serving fleet: health-gated failover over N replicas.

``python -m estorch_tpu.serve route --fleet fleet.json`` (serve/fleet.py
spawns the replicas and runs this router in-process) or
``... route --replicas r0=127.0.0.1:8321,r1=127.0.0.1:8322`` over
replicas managed elsewhere.  Stdlib-only, jax-free, and runnable as a
plain file (``python estorch_tpu/serve/router.py``) — the sidecar
discipline: the layer that answers "is the fleet up?" must not depend
on the runtime whose death it exists to survive.

Routes:

* ``POST /predict`` — forwarded to one healthy replica, chosen by
  capacity (``/stats`` queue depth × ``request_ms`` p99 ≈ expected
  wait); connect/timeout/5xx failures retry on a DIFFERENT replica
  under a bounded budget with exponential backoff + jitter.
  Idempotent-safe: a request is never replayed after response bytes
  were written to the client, and ``/reload`` (non-idempotent) is never
  retried at all;
* ``GET /healthz`` / ``GET /stats`` / ``GET /metrics`` — router
  liveness, per-replica breaker/health detail (+ the collector-
  discovery stanza), Prometheus exposition with per-replica labeled
  gauges and true ``route_s``/``upstream_s`` histograms;
* ``POST /rollout {"path": bundle}`` / ``GET /rollout`` — canary
  rollout, delegated to the fleet supervisor when one is attached
  (serve/fleet.py owns the state machine; a bare router answers 409);
* ``POST /scale {"replicas": N}`` / ``GET /scale`` — the fleet's
  autoscaler admin surface (obs/agg/autoscale.py actuates here),
  delegated to the fleet like /rollout; a bare router answers 409.

Per-replica circuit breakers (docs/serving.md "Fleet"): consecutive
failures open the breaker (no traffic), a timed half-open probe admits
one trial, success closes it.  The health poll doubles as the probe, so
a respawned replica re-enters rotation within one poll interval without
sacrificing a client request.  Optional tail hedging duplicates a
request that outlives the observed upstream p99 onto a second replica —
first answer wins, the loser's connection is torn down (``hedged`` /
``hedge_wins`` counters).

Trace ids: the router mints ``r<N>`` (or honors an incoming
``X-Trace-Id``), forwards it upstream — where the replica's batcher
records it against the batch dispatch — and echoes it plus
``X-Upstream`` back, so one slow answer is attributable to one replica
in ``obs trace``.

SIGTERM drains: stop accepting, answer everything in flight, exit 0.
"""

from __future__ import annotations

import argparse
import http.client
import itertools
import json
import os
import random
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

if __package__:
    from ..obs.counters import Counters
    from ..obs.hist import Histogram, Histograms
    from ..obs.export.prometheus import (metric_name, render_exposition,
                                         _escape_label)
    from ..obs.tracing import (PARENT_SPAN_HEADER, SAMPLED_HEADER,
                               TRACES_FILENAME, ProcessTracer,
                               make_segment, traces_payload)
else:  # file-run (wedged-jax host): load siblings without any package init
    import importlib.util

    def _load(name: str, *rel: str):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            *rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _counters = _load("_estorch_obs_counters", os.pardir, "obs",
                      "counters.py")
    _hist = _load("_estorch_obs_hist", os.pardir, "obs", "hist.py")
    _prom = _load("_estorch_obs_prometheus", os.pardir, "obs", "export",
                  "prometheus.py")
    _tracing = _load("_estorch_obs_tracing", os.pardir, "obs",
                     "tracing.py")
    Counters = _counters.Counters
    Histogram = _hist.Histogram
    Histograms = _hist.Histograms
    metric_name = _prom.metric_name
    render_exposition = _prom.render_exposition
    _escape_label = _prom._escape_label
    PARENT_SPAN_HEADER = _tracing.PARENT_SPAN_HEADER
    SAMPLED_HEADER = _tracing.SAMPLED_HEADER
    TRACES_FILENAME = _tracing.TRACES_FILENAME
    ProcessTracer = _tracing.ProcessTracer
    make_segment = _tracing.make_segment
    traces_payload = _tracing.traces_payload

DRAIN_GRACE_S = 15.0

BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
# numeric encoding for the exported gauge (docs/serving.md "Fleet")
BREAKER_STATE_CODE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1,
                      BREAKER_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure breaker: closed → open (after
    ``fail_threshold`` failures in a row) → half-open (one probe after
    ``open_s``) → closed on success / open on failure.  A success from
    ANY state closes — the health poll is the probe, and a replica that
    answers it is back (its respawn may sit on a new port, so the probe
    result is fresher than any stale failure streak)."""

    def __init__(self, fail_threshold: int = 3, open_s: float = 1.0):
        self.fail_threshold = int(fail_threshold)
        self.open_s = float(open_s)
        self.state = BREAKER_CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.opens_total = 0
        self._probe_inflight = False
        self._lock = threading.Lock()

    def allow(self) -> bool:
        """May a request be sent now?  Half-open admits exactly one
        in-flight probe; its outcome decides the next state."""
        with self._lock:
            if self.state == BREAKER_CLOSED:
                return True
            if self.state == BREAKER_OPEN:
                if time.monotonic() - self.opened_at < self.open_s:
                    return False
                self.state = BREAKER_HALF_OPEN
                self._probe_inflight = False
            # half-open: one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self.state = BREAKER_CLOSED
            self.failures = 0
            self._probe_inflight = False

    def record_failure(self) -> bool:
        """Returns True when this failure OPENED the breaker."""
        with self._lock:
            self.failures += 1
            opened = False
            if (self.state == BREAKER_HALF_OPEN
                    or (self.state == BREAKER_CLOSED
                        and self.failures >= self.fail_threshold)):
                self.state = BREAKER_OPEN
                self.opened_at = time.monotonic()
                self.opens_total += 1
                opened = True
            self._probe_inflight = False
            return opened


class Replica:
    """One upstream: address + breaker + the last health-poll facts."""

    def __init__(self, name: str, address: str, *,
                 fail_threshold: int = 3, open_s: float = 1.0):
        self.name = str(name)
        self.address = _strip_scheme(address)
        self.breaker = CircuitBreaker(fail_threshold, open_s)
        self.hist = Histogram()  # per-replica upstream latency
        self.lock = threading.Lock()
        # health facts, overwritten whole by the poll thread
        self.health: dict = {"polled": False}
        self.inflight = 0
        self.requests = 0
        self.failures = 0
        # set by retire_replica: out of selection immediately (the
        # fleet notifies the router BEFORE killing a retiring replica)
        self.retiring = False

    def snapshot(self) -> dict:
        h = dict(self.health)
        return {
            "name": self.name,
            "address": self.address,
            "breaker": self.breaker.state,
            "breaker_opens": self.breaker.opens_total,
            "retiring": self.retiring,
            "inflight": self.inflight,
            "requests": self.requests,
            "failures": self.failures,
            "upstream_p99_ms": (round(self.hist.quantile(0.99) * 1e3, 3)
                                if self.hist.count else None),
            **{k: h.get(k) for k in ("polled", "ok", "draining",
                                     "queue_depth", "p99_ms", "age_s",
                                     "error", "version")},
        }


def _strip_scheme(address: str) -> str:
    if "://" in address:
        address = address.split("://", 1)[1]
    return address.rstrip("/")


def write_port_file(path: str, host: str, port: int) -> None:
    """Atomically publish ``{host, port, pid}`` — the bind announcement
    the fleet's ``_check_starting`` (and any launcher passing
    ``--port-file``) polls for.  One definition: server, router, and
    fleet entry points all write the same schema."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"host": host, "port": int(port),
                   "pid": os.getpid()}, f)
    os.replace(tmp, path)


class UpstreamError(Exception):
    """One failed upstream attempt — always safe to retry on a
    DIFFERENT replica (/predict is pure and nothing reached the
    client); ``breaker`` says whether it counts as a death (transport
    failures and 5xx do, 503 backpressure does not)."""

    def __init__(self, msg: str, *, breaker: bool):
        super().__init__(msg)
        self.breaker = breaker


class Router:
    """Health-gated load balancer + the fleet's one client-facing port."""

    def __init__(
        self,
        replicas: list[tuple[str, str]],
        *,
        host: str = "127.0.0.1",
        port: int = 8400,
        retry_budget: int = 2,
        backoff_base_s: float = 0.025,
        backoff_max_s: float = 0.5,
        upstream_timeout_s: float = 10.0,
        poll_interval_s: float = 0.25,
        poll_timeout_s: float = 1.0,
        breaker_failures: int = 3,
        breaker_open_s: float = 1.0,
        hedge: bool = False,
        hedge_min_ms: float = 25.0,
        hedge_quantile: float = 0.99,
        shadow_queue: int = 64,
        rollout_cb=None,
        scale_cb=None,
        serve_http: bool = True,
        run_dir: str | None = None,
        trace_head_every: int = 16,
    ):
        self.counters = Counters()
        self.hists = Histograms()
        # distributed tracing (obs/tracing.py): per-hop segments,
        # tail-sampled when the route span ends; ``run_dir`` enables the
        # traces.jsonl flush beside the heartbeat/port files
        self.tracer = ProcessTracer(
            "router", counters=self.counters, hists=self.hists,
            hist_name="router/route_s", head_every=trace_head_every,
            path=(os.path.join(run_dir, TRACES_FILENAME)
                  if run_dir else None))
        self.retry_budget = int(retry_budget)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.poll_timeout_s = float(poll_timeout_s)
        self.breaker_failures = int(breaker_failures)
        self.breaker_open_s = float(breaker_open_s)
        self.hedge = bool(hedge)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_quantile = float(hedge_quantile)
        self._rollout_cb = rollout_cb
        self._scale_cb = scale_cb
        # desired fleet size, set by the supervisor on every scale
        # decision — exported as a gauge so the dash can show
        # desired-vs-actual from the store alone
        self.desired_replicas: int | None = None
        self._replicas: dict[str, Replica] = {}
        self._replicas_lock = threading.Lock()
        for name, addr in replicas:
            self.add_replica(name, addr)
        self._rr = itertools.count()
        self._req_seq = itertools.count(1)
        self._rng = random.Random(0xE57)  # backoff jitter only
        self._started_mono = time.monotonic()
        self.draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._inflight_zero = threading.Event()
        self._inflight_zero.set()
        # canary shadow state (armed by the fleet during a rollout)
        self._canary_lock = threading.Lock()
        self._canary: dict | None = None
        self._shadow_q: "list" = []  # bounded, guarded by _canary_lock
        self._shadow_q_max = int(shadow_queue)
        self._shadow_wake = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._httpd = None
        if serve_http:
            self._httpd = _RouterHttpd((host, int(port)),
                                       _make_handler(self))
            self.host, self.port = self._httpd.server_address[:2]

    # ------------------------------------------------------------ replicas

    def add_replica(self, name: str, address: str) -> None:
        with self._replicas_lock:
            self._replicas[name] = Replica(
                name, address, fail_threshold=self.breaker_failures,
                open_s=self.breaker_open_s)

    def update_replica(self, name: str, address: str) -> None:
        """A respawned replica comes back on a fresh port: swap the
        address, reset health (the poll re-learns it), KEEP the breaker
        — the probe closing it is the readmission protocol."""
        with self._replicas_lock:
            rep = self._replicas.get(name)
            if rep is None:
                self._replicas[name] = Replica(
                    name, address, fail_threshold=self.breaker_failures,
                    open_s=self.breaker_open_s)
                return
            rep.address = _strip_scheme(address)
            rep.health = {"polled": False}
            rep.retiring = False

    def retire_replica(self, name: str) -> bool:
        """Take ``name`` out of selection IMMEDIATELY (scale-down step
        one): no new request reaches it, in-flight answers complete, the
        health poll keeps watching it drain.  The fleet calls this
        BEFORE sending SIGTERM — the ordering that makes a retirement
        cost zero client errors."""
        with self._replicas_lock:
            rep = self._replicas.get(name)
            if rep is None:
                return False
            rep.retiring = True
        self.counters.inc("router_replicas_retired_total")
        return True

    def remove_replica(self, name: str) -> bool:
        """Forget ``name`` entirely (the retired process is dead): its
        breaker, histogram and health facts go with it — a future slot
        reusing the name starts clean."""
        with self._replicas_lock:
            return self._replicas.pop(name, None) is not None

    def replicas(self) -> list[Replica]:
        with self._replicas_lock:
            return list(self._replicas.values())

    # ------------------------------------------------------------ lifecycle

    def start_background(self) -> None:
        for target, name in ((self._poll_loop, "router-poll"),
                             (self._shadow_loop, "router-shadow")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self._httpd is not None:
            t = threading.Thread(target=self._httpd.serve_forever,
                                 kwargs={"poll_interval": 0.1},
                                 name="router-http", daemon=True)
            t.start()
            self._threads.append(t)

    def shutdown(self, drain: bool = True) -> dict:
        self.draining = True
        self._stop.set()
        self._shadow_wake.set()
        if self._httpd is not None:
            self._httpd.shutdown()
        if drain:
            self._inflight_zero.wait(DRAIN_GRACE_S)
        if self._httpd is not None:
            self._httpd.server_close()
        self.tracer.flush()  # sampled segments outlive the process
        return {"drained": True, "clean": self._inflight_zero.is_set(),
                "counters": self.counters.snapshot()}

    def track_request(self):
        with self._inflight_lock:
            self._inflight += 1
            self._inflight_zero.clear()

    def untrack_request(self):
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._inflight_zero.set()

    # ------------------------------------------------------------- health

    def _poll_one(self, rep: Replica) -> None:
        conn = http.client.HTTPConnection(
            *_split(rep.address), timeout=self.poll_timeout_s)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = json.loads(resp.read().decode() or "{}")
            facts = {
                "polled": True,
                "ok": bool(body.get("ok")),
                "draining": bool(body.get("draining")),
                "queue_depth": body.get("queue_depth"),
                "version": body.get("version"),
                "age_s": (body.get("heartbeat") or {}).get("age_s"),
                "error": None,
            }
            # capacity detail rides /stats (request_ms p99 from the
            # replica's own histograms) — best-effort: a replica whose
            # /stats is momentarily slow is still healthy
            try:
                conn.request("GET", "/stats")
                stats = json.loads(conn.getresponse().read().decode())
                lat = stats.get("request_ms") or {}
                facts["p99_ms"] = lat.get("p99")
                facts["queue_depth"] = stats.get(
                    "queue_depth", facts["queue_depth"])
            except (OSError, ValueError, http.client.HTTPException):
                facts["p99_ms"] = rep.health.get("p99_ms")
            with rep.lock:
                rep.health = facts
            if facts["ok"]:
                # the poll IS the half-open probe: an answering replica
                # re-enters rotation without risking a client request
                if rep.breaker.state != BREAKER_CLOSED:
                    self.counters.inc("router_breaker_closes_total")
                rep.breaker.record_success()
            elif facts["draining"]:
                # draining answers politely but must leave rotation;
                # not a death — no breaker-open storm for a clean drain
                pass
        except (OSError, ValueError, http.client.HTTPException) as e:
            with rep.lock:
                rep.health = {"polled": True, "ok": False,
                              "error": f"{type(e).__name__}: {e}",
                              "draining": rep.health.get("draining"),
                              "queue_depth": None,
                              "p99_ms": rep.health.get("p99_ms"),
                              "age_s": None,
                              "version": rep.health.get("version")}
            if rep.breaker.record_failure():
                self.counters.inc("router_breaker_opens_total")
        finally:
            conn.close()

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            for rep in self.replicas():
                self._poll_one(rep)
            self._stop.wait(self.poll_interval_s)

    # ------------------------------------------------------------- routing

    def _eligible(self, exclude: set[str]
                  ) -> tuple[list[Replica], list[Replica]]:
        """(healthy closed-breaker replicas, breaker-gated candidates).
        ``allow()`` is NOT called here — it claims the single half-open
        probe slot, so only :meth:`pick`'s chosen candidate may call it
        (claiming it for a candidate that loses the pick would lock a
        recovering replica out until the next health poll)."""
        c = self._canary  # one read: end_canary can null it mid-pick
        canary = c["name"] if c else None
        healthy, probes = [], []
        for rep in self.replicas():
            if rep.name in exclude or rep.name == canary or rep.retiring:
                continue
            h = rep.health
            down = h.get("polled") and (not h.get("ok")
                                        or h.get("draining"))
            if rep.breaker.state == BREAKER_CLOSED:
                if not down:
                    healthy.append(rep)
            else:
                probes.append(rep)
        return healthy, probes

    def pick(self, exclude: set[str] = frozenset()) -> Replica | None:
        """Least-expected-wait among eligible replicas: queue depth (its
        own + our in-flight) × observed p99 service time, round-robin on
        ties so equal replicas share load.  Half-open probes get client
        traffic only when no healthy replica exists, best-scored first,
        claiming the probe slot only for the one actually returned."""
        healthy, probes = self._eligible(set(exclude))
        rr = next(self._rr)

        def ranked(cands):
            def score(item):
                i, rep = item
                h = rep.health
                q = h.get("queue_depth")
                depth = (0 if q is None else float(q)) + rep.inflight
                p99 = h.get("p99_ms")
                service = max(float(p99) if p99 else 0.0, 1.0) / 1e3
                return (depth * service, (i + rr) % len(cands))

            return [rep for _i, rep in
                    sorted(enumerate(cands), key=score)]

        if healthy:
            return ranked(healthy)[0]
        for rep in ranked(probes):
            if rep.breaker.allow():
                return rep
        return None

    # one upstream try; raises UpstreamError on any failed attempt
    def _upstream_predict(self, rep: Replica, body: bytes, trace: str,
                          cancel_box: dict | None = None,
                          parent_span: str | None = None,
                          sampled: bool = False) -> tuple[int, bytes]:
        conn = http.client.HTTPConnection(
            *_split(rep.address), timeout=self.upstream_timeout_s)
        if cancel_box is not None:
            cancel_box["conn"] = conn
        try:
            try:
                headers = {
                    "Content-Type": "application/json",
                    "X-Trace-Id": trace,
                }
                if parent_span:
                    # the replica's request span parents to THIS leg, so
                    # assembly can tell retry/hedge legs apart
                    headers[PARENT_SPAN_HEADER] = parent_span
                if sampled:
                    # this hop already knows the trace is interesting
                    # (retry/hedge leg): force the downstream sampler
                    headers[SAMPLED_HEADER] = "1"
                conn.request("POST", "/predict", body, headers)
                resp = conn.getresponse()
                data = resp.read()
            except (TimeoutError, OSError,
                    http.client.HTTPException) as e:
                # connect refused / reset / read timeout: the CLIENT saw
                # no bytes, and /predict is pure — safe on another
                # replica.  Counts toward the breaker.
                raise UpstreamError(f"{type(e).__name__}: {e}",
                                    breaker=True) from e
            except Exception as e:
                # a hedge cancel races this read: the winner's thread
                # calls conn.close() under us, and http.client's
                # internals can surface that as errors outside the
                # tuple above (e.g. AttributeError from a half-torn
                # response object mid-read).  Only when WE cancelled is
                # that expected — map it to the failed-attempt path so
                # the loser records its cancelled leg instead of dying
                # as an unhandled thread exception.
                if cancel_box is not None and cancel_box.get("cancelled"):
                    raise UpstreamError(
                        f"cancelled mid-read ({type(e).__name__}: {e})",
                        breaker=False) from e
                raise
            if resp.status == 503:
                # shed or draining: alive but refusing — try another
                # replica, but don't open the breaker for backpressure
                raise UpstreamError(f"503 from {rep.name}",
                                    breaker=False)
            if resp.status >= 500:
                raise UpstreamError(
                    f"{resp.status} from {rep.name}: "
                    f"{data[:200].decode(errors='replace')}",
                    breaker=True)
            return resp.status, data
        finally:
            conn.close()

    def _attempt(self, rep: Replica, body: bytes, trace: str,
                 cancel_box: dict | None = None, *,
                 parent_span: str | None = None, attempt: int = 0,
                 hedge: bool = False,
                 sampled: bool = False) -> tuple[int, bytes]:
        """One accounted attempt: breaker + latency + counters + one
        ``upstream`` trace leg (retry legs carry their attempt index,
        hedge legs their flag, a cancelled loser its ``cancelled``)."""
        with rep.lock:
            rep.inflight += 1
            rep.requests += 1
        t0 = time.perf_counter()
        leg_span = self.tracer.span_id()
        try:
            status, data = self._upstream_predict(
                rep, body, trace, cancel_box, parent_span=leg_span,
                sampled=sampled or hedge)
        except UpstreamError as e:
            with rep.lock:
                rep.inflight -= 1
            cancelled = bool(cancel_box is not None
                             and cancel_box.get("cancelled"))
            self.tracer.add(make_segment(
                trace, leg_span, parent_span, "router", "upstream",
                t0, time.perf_counter() - t0,
                attrs={"replica": rep.name, "attempt": attempt,
                       "hedge": hedge, "cancelled": cancelled,
                       "error": str(e)}))
            if cancelled:
                # WE closed this connection (hedge loser): the replica
                # is healthy-but-slow, not dead — charging its breaker
                # would flap a slow replica out of rotation, the exact
                # 'overload is not death' mistake the 503 rule avoids
                raise
            with rep.lock:
                rep.failures += 1
            self.counters.inc("router_upstream_failures_total")
            if e.breaker and rep.breaker.record_failure():
                self.counters.inc("router_breaker_opens_total")
            raise
        dt = time.perf_counter() - t0
        with rep.lock:
            rep.inflight -= 1
        rep.breaker.record_success()
        rep.hist.observe(dt)
        self.hists.observe("router/upstream_s", dt, exemplar=trace)
        self.tracer.add(make_segment(
            trace, leg_span, parent_span, "router", "upstream", t0, dt,
            attrs={"replica": rep.name, "attempt": attempt,
                   "hedge": hedge, "status": status}))
        return status, data

    def _hedge_deadline_s(self) -> float | None:
        """Hedge after the observed upstream tail (p-``hedge_quantile``),
        floored at ``hedge_min_ms`` — hedging below the floor would
        double most traffic, not just the tail."""
        if not self.hedge:
            return None
        q = self.hists.quantile("router/upstream_s", self.hedge_quantile)
        if q is None:
            return self.hedge_min_ms / 1e3
        return max(q, self.hedge_min_ms / 1e3)

    def route_predict(self, body: bytes, trace: str,
                      parent_span: str | None = None,
                      forced: bool = False
                      ) -> tuple[int, bytes, str | None]:
        """Forward one /predict; returns (status, body, replica name).
        Exhausted budget / no eligible replica answers 503 here — the
        handler writes it; nothing is ever retried after that write.
        The whole routing decision is one ``route`` trace span; its end
        is where the tail sampler judges the trace."""
        t0 = time.perf_counter()
        route_span = self.tracer.span_id()
        flags = {"retried": False, "hedged": False, "breaker": False}
        tried: set[str] = set()
        last_err = "no eligible replica"
        for attempt in range(1 + self.retry_budget):
            rep = self.pick(exclude=tried)
            if rep is None:
                break
            tried.add(rep.name)
            if rep.breaker.state != BREAKER_CLOSED:
                flags["breaker"] = True
            if attempt:
                flags["retried"] = True
                self.counters.inc("router_retries_total")
                # exponential backoff + jitter: a mass failover must not
                # stampede the survivors in lockstep
                base = min(self.backoff_base_s * (2 ** (attempt - 1)),
                           self.backoff_max_s)
                time.sleep(base * (0.5 + self._rng.random()))
            try:
                status, data, winner = self._attempt_maybe_hedged(
                    rep, body, trace, first=(attempt == 0), tried=tried,
                    route_span=route_span, attempt=attempt, flags=flags,
                    sampled=forced)
            except UpstreamError as e:
                flags["breaker"] = flags["breaker"] or e.breaker
                last_err = str(e)
                continue
            self.counters.inc("router_requests_total")
            dt = time.perf_counter() - t0
            self._observe_live(winner, body, data, status, dt)
            self.hists.observe("router/route_s", dt, exemplar=trace)
            self.tracer.add(make_segment(
                trace, route_span, parent_span, "router", "route", t0,
                dt, attrs={"status": status, "replica": winner.name,
                           "attempts": attempt + 1}))
            self.tracer.finish(trace, dt, error=status >= 400,
                               forced=forced, **flags)
            return status, data, winner.name
        self.counters.inc("router_no_upstream_total")
        dt = time.perf_counter() - t0
        self.hists.observe("router/route_s", dt, exemplar=trace)
        self.tracer.add(make_segment(
            trace, route_span, parent_span, "router", "route", t0, dt,
            attrs={"status": 503, "attempts": len(tried),
                   "error": last_err}))
        self.tracer.finish(trace, dt, error=True, forced=forced, **flags)
        body_out = json.dumps({
            "error": f"no healthy upstream after {len(tried)} attempt(s)"
                     f" — last: {last_err}",
            "trace": trace,
        }).encode()
        return 503, body_out, None

    def _attempt_maybe_hedged(self, rep: Replica, body: bytes, trace: str,
                              *, first: bool, tried: set[str],
                              route_span: str | None = None,
                              attempt: int = 0, flags: dict | None = None,
                              sampled: bool = False
                              ) -> tuple[int, bytes, Replica]:
        """First attempt with optional tail hedging: when the primary
        outlives the hedge deadline, duplicate onto a second replica and
        take whichever answers first (the loser's connection is torn
        down).  Returns (status, body, WINNING replica) — the client's
        X-Upstream must name the replica that actually answered, not
        the stalled primary.  Retries (non-first attempts) never hedge —
        the budget is already paying for them."""
        deadline = self._hedge_deadline_s() if first else None
        if deadline is None:
            status, data = self._attempt(rep, body, trace,
                                         parent_span=route_span,
                                         attempt=attempt, sampled=sampled)
            return status, data, rep

        results: list = []
        done = threading.Event()
        lock = threading.Lock()

        def run(target: Replica, box: dict, hedge_leg: bool) -> None:
            try:
                out = self._attempt(target, body, trace, cancel_box=box,
                                    parent_span=route_span,
                                    attempt=attempt, hedge=hedge_leg,
                                    sampled=sampled)
                with lock:
                    results.append((target, out, None))
            except UpstreamError as e:
                with lock:
                    results.append((target, None, e))
            done.set()

        primary_box: dict = {}
        t_p = threading.Thread(target=run, args=(rep, primary_box, False),
                               name="router-primary", daemon=True)
        t_p.start()
        hedged = False
        hedge_rep = None
        hedge_box: dict = {}
        t_h = None
        if not done.wait(deadline):
            hedge_rep = self.pick(exclude=tried | {rep.name})
            if hedge_rep is not None:
                tried.add(hedge_rep.name)
                hedged = True
                if flags is not None:
                    flags["hedged"] = True
                self.counters.inc("router_hedged_total")
                t_h = threading.Thread(target=run,
                                       args=(hedge_rep, hedge_box, True),
                                       name="router-hedge", daemon=True)
                t_h.start()
        # wait until SOME attempt succeeds or all in flight have failed
        outstanding = 1 + (1 if hedged else 0)
        while True:
            done.wait(self.upstream_timeout_s + 1.0)
            with lock:
                done.clear()
                wins = [r for r in results if r[1] is not None]
                fails = [r for r in results if r[1] is None]
                if wins:
                    winner, out, _ = wins[0]
                    break
                if len(fails) >= outstanding:
                    raise fails[-1][2]
        if hedged:
            if winner is hedge_rep:
                self.counters.inc("router_hedge_wins_total")
                loser_box, loser_t = primary_box, t_p
            else:
                loser_box, loser_t = hedge_box, t_h
            # cancel the loser: mark FIRST (so its _attempt knows the
            # failure is ours, not the replica's — no breaker charge),
            # then close the socket to abandon the duplicate answer; an
            # already-broken socket is the same outcome
            loser_box["cancelled"] = True
            conn = loser_box.get("conn")
            if conn is not None:
                import contextlib

                with contextlib.suppress(OSError):
                    conn.close()
            # give the aborted loser a beat to record its cancelled leg
            # BEFORE the route span ends and the tail sampler judges the
            # trace — a closed socket raises immediately, so this join
            # costs microseconds on the happy path and is best-effort
            # (a straggler leg still lands via the decided-trace cache)
            if loser_t is not None:
                loser_t.join(0.25)
        return out[0], out[1], winner

    # ------------------------------------------------------------- canary

    def start_canary(self, name: str, fraction: float,
                     parity_max: int = 32) -> None:
        """Quarantine ``name``: it leaves live rotation IMMEDIATELY (a
        client must never see an unpromoted canary's answers — the fleet
        calls this BEFORE reloading it), but shadow sampling stays off
        until :meth:`arm_canary` — a sample taken mid-reload would
        compare the canary's OLD engine against itself and wave a bad
        bundle through the parity gate."""
        with self._canary_lock:
            self._canary = {
                "name": name, "fraction": float(fraction),
                "parity_max": int(parity_max), "started": time.time(),
                "armed": False,
                "canary_lat": [], "incumbent_lat": [], "parity": [],
                "shadow_sent": 0, "shadow_errors": 0, "shadow_dropped": 0,
            }
            self._shadow_q.clear()

    def arm_canary(self) -> None:
        """Begin shadow sampling (the canary now serves the NEW bundle);
        buffers reset so nothing from the reload window leaks in."""
        with self._canary_lock:
            c = self._canary
            if c is None:
                return
            c["armed"] = True
            c["canary_lat"].clear()
            c["incumbent_lat"].clear()
            c["parity"].clear()
            c["shadow_sent"] = c["shadow_errors"] = 0
            c["shadow_dropped"] = 0
            self._shadow_q.clear()

    def end_canary(self) -> dict | None:
        with self._canary_lock:
            snap, self._canary = self._canary, None
            self._shadow_q.clear()
        return snap

    def canary_snapshot(self) -> dict | None:
        with self._canary_lock:
            if self._canary is None:
                return None
            c = self._canary
            return {
                "name": c["name"], "fraction": c["fraction"],
                "started": c["started"],
                "canary_lat": list(c["canary_lat"]),
                "incumbent_lat": list(c["incumbent_lat"]),
                "parity": list(c["parity"]),
                "shadow_sent": c["shadow_sent"],
                "shadow_errors": c["shadow_errors"],
                "shadow_dropped": c["shadow_dropped"],
            }

    def _observe_live(self, rep: Replica, body: bytes, data: bytes,
                      status: int, latency_s: float) -> None:
        """Sample live traffic into the rollout comparison while a
        canary is armed: the sampled request is enqueued for the shadow
        worker, which probes canary AND a live incumbent through the
        IDENTICAL path (bounded queue — shadowing must never add latency
        to, or block, the live path)."""
        del rep, latency_s
        with self._canary_lock:
            c = self._canary
            if c is None or not c["armed"] or status != 200:
                return
            if self._rng.random() >= c["fraction"]:
                return
            if len(self._shadow_q) >= self._shadow_q_max:
                c["shadow_dropped"] += 1
                return
            self._shadow_q.append((body, data))
        self._shadow_wake.set()

    def _shadow_probe(self, name: str, body: bytes
                      ) -> tuple[bool, bytes, float]:
        with self._replicas_lock:
            rep = self._replicas.get(name)
        if rep is None:
            return False, b"", 0.0
        t0 = time.perf_counter()
        try:
            status, data = self._upstream_predict(
                rep, body, f"shadow-{next(self._req_seq)}")
            return status == 200, data, time.perf_counter() - t0
        except UpstreamError:
            return False, b"", 0.0

    def _shadow_loop(self) -> None:
        """Paired probes: each sampled request is sent to the canary AND
        to a live incumbent through the SAME code path (fresh
        connection, lone arrival — so a sparse shadow's batching-window
        cost hits both sides equally; comparing shadow probes against
        the live path's coalesced latencies systematically biased
        against the canary).  The parity row compares the canary's
        answer against the LIVE answer the client actually got."""
        while not self._stop.is_set():
            self._shadow_wake.wait(0.2)
            while True:
                with self._canary_lock:
                    c = self._canary
                    if c is None or not self._shadow_q:
                        self._shadow_wake.clear()
                        break
                    body, live_data = self._shadow_q.pop(0)
                    canary_name = c["name"]
                ok, data, dt = self._shadow_probe(canary_name, body)
                inc = self.pick()  # excludes the canary by definition
                inc_ok = inc_dt = None
                if inc is not None:
                    inc_ok, _, inc_dt = self._shadow_probe(inc.name,
                                                           body)
                with self._canary_lock:
                    c = self._canary
                    if c is None or c["name"] != canary_name:
                        continue  # rollout ended while we were in flight
                    c["shadow_sent"] += 1
                    if not ok:
                        c["shadow_errors"] += 1
                        continue
                    if len(c["canary_lat"]) < 10000:
                        c["canary_lat"].append(dt)
                    if inc_ok and len(c["incumbent_lat"]) < 10000:
                        c["incumbent_lat"].append(inc_dt)
                    if len(c["parity"]) < c["parity_max"]:
                        c["parity"].append((
                            body.decode(errors="replace"),
                            _action_of(live_data),
                            _action_of(data)))

    # ------------------------------------------------------------ surfaces

    def health(self) -> dict:
        reps = [r.snapshot() for r in self.replicas()]
        healthy = sum(1 for r in reps
                      if r["breaker"] == BREAKER_CLOSED and r.get("ok"))
        return {
            "ok": not self.draining and healthy > 0,
            "draining": self.draining,
            "role": "router",
            "replicas_total": len(reps),
            "replicas_healthy": healthy,
            "uptime_s": round(time.monotonic() - self._started_mono, 3),
            "pid": os.getpid(),
        }

    def rollout_status(self) -> dict:
        if self._rollout_cb is None:
            return {"supported": False}
        return {"supported": True, **self._rollout_cb("status", None)}

    def scale_status(self) -> dict:
        if self._scale_cb is None:
            return {"supported": False}
        return {"supported": True, **self._scale_cb("status", None)}

    def stats(self) -> dict:
        lat = {}
        h = self.hists.get("router/route_s")
        if h is not None and h.count:
            lat = {"p50": round(h.quantile(0.5) * 1e3, 3),
                   "p99": round(h.quantile(0.99) * 1e3, 3)}
        snap = self.canary_snapshot()
        return {
            "role": "router",
            "replicas": [r.snapshot() for r in self.replicas()],
            "counters": self.counters.snapshot(),
            "route_ms": lat,
            "canary": ({k: v for k, v in snap.items()
                        if k not in ("canary_lat", "incumbent_lat",
                                     "parity")}
                       if snap else None),
            "rollout": self.rollout_status(),
            "scale": self.scale_status(),
            "collector_target": self._collector_target(),
        }

    def _collector_target(self) -> dict:
        host = getattr(self, "host", "127.0.0.1")
        if host in ("0.0.0.0", "::", ""):
            import socket as _socket

            host = _socket.getfqdn() or _socket.gethostname()
        port = getattr(self, "port", 0)
        return {"name": f"router-{host}-{port}",
                "url": f"http://{host}:{port}/metrics"}

    def metrics(self) -> str:
        """Prometheus exposition: flat counters + route/upstream
        histograms through the shared encoder, then per-replica labeled
        gauges (the collector-idiom blocks the fleet dash reads)."""
        extra = {
            "uptime_seconds": round(
                time.monotonic() - self._started_mono, 3),
            "draining": 1.0 if self.draining else 0.0,
        }
        if self.desired_replicas is not None:
            extra["router_desired_replicas"] = float(self.desired_replicas)
        body = render_exposition(
            self.counters.snapshot(), None, up=not self.draining,
            extra_gauges=extra,
            histograms=self.hists.export() or None)
        lines = [body.rstrip("\n")]
        gauges = (
            ("router_replica_up", "1 while the replica answers health "
                                  "polls",
             lambda r: 1.0 if (r.health.get("ok")
                               and not r.health.get("draining")) else 0.0),
            ("router_breaker_state", "0 closed / 1 half-open / 2 open",
             lambda r: float(BREAKER_STATE_CODE[r.breaker.state])),
            ("router_replica_queue_depth", "replica queue depth at last "
                                           "poll",
             lambda r: float(r.health.get("queue_depth") or 0.0)),
            ("router_upstream_p99_s", "observed p99 of this replica's "
                                      "answers through the router",
             lambda r: (r.hist.quantile(0.99)
                        if r.hist.count else float("nan"))),
            ("router_replica_retries_total",
             "failed attempts charged to this replica",
             lambda r: float(r.failures)),
        )
        for name, help_, get in gauges:
            metric = metric_name(name)
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# HELP {metric} {help_}")
            lines.append(f"# TYPE {metric} {kind}")
            for rep in self.replicas():
                lines.append(
                    f'{metric}{{replica="{_escape_label(rep.name)}"}} '
                    f"{_fmt_val(get(rep))}")
        return "\n".join(lines) + "\n"


def _fmt_val(v: float) -> str:
    import math

    if math.isnan(v):
        return "NaN"
    return f"{v:g}"


def _split(address: str) -> tuple[str, int]:
    host, _, port = address.partition(":")
    return host, int(port or 80)


def _since_of(path: str) -> int:
    """``since`` cursor of a ``/traces?since=N`` request path (0 when
    absent/garbage — a bad cursor degrades to a full recent-window
    answer, never a 400 on a scrape path)."""
    query = path.partition("?")[2]
    for part in query.split("&"):
        key, _, value = part.partition("=")
        if key == "since":
            try:
                return int(value)
            except ValueError:
                return 0
    return 0


def _action_of(data: bytes):
    try:
        return json.loads(data.decode()).get("action")
    except (ValueError, AttributeError):
        return None


class _RouterHttpd(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


def _make_handler(router: Router):
    class RouterHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def _reply(self, code: int, body: bytes, ctype: str,
                   extra: dict | None = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            if router.draining:
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code: int, payload: dict,
                        extra: dict | None = None) -> None:
            self._reply(code, json.dumps(payload, default=float).encode(),
                        "application/json", extra)

        def do_GET(self):
            if self.path == "/healthz":
                h = router.health()
                self._reply_json(200 if h["ok"] else 503, h)
            elif self.path == "/stats":
                self._reply_json(200, router.stats())
            elif self.path == "/metrics":
                self._reply(200, router.metrics().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/rollout":
                self._reply_json(200, router.rollout_status())
            elif self.path == "/scale":
                self._reply_json(200, router.scale_status())
            elif self.path.split("?", 1)[0] == "/traces":
                self._reply_json(200, traces_payload(
                    router.tracer, _since_of(self.path),
                    hists=router.hists))
            else:
                self._reply_json(404, {"error": f"no route {self.path!r}"})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b""
            if self.path == "/predict":
                self._predict(raw)
                return
            try:
                data = json.loads(raw) if raw else {}
            except ValueError as e:
                self._reply_json(400, {"error": f"bad request body: {e}"})
                return
            if self.path == "/rollout":
                self._rollout(data)
            elif self.path == "/scale":
                self._scale(data)
            else:
                self._reply_json(404, {"error": f"no route {self.path!r}"})

        def _predict(self, raw: bytes) -> None:
            if router.draining:
                self._reply_json(503, {"error": "draining"})
                return
            trace = (self.headers.get("X-Trace-Id")
                     or f"r{next(router._req_seq)}")
            parent_span = self.headers.get(PARENT_SPAN_HEADER) or None
            forced = self.headers.get(SAMPLED_HEADER) == "1"
            router.track_request()
            try:
                status, body, upstream = router.route_predict(
                    raw, trace, parent_span=parent_span, forced=forced)
                extra = {"X-Trace-Id": trace}
                if upstream:
                    extra["X-Upstream"] = upstream
                elif status == 503:
                    extra["Retry-After"] = "1"
                self._reply(status, body, "application/json", extra)
            finally:
                router.untrack_request()

        def _rollout(self, data: dict) -> None:
            if router._rollout_cb is None:
                self._reply_json(409, {
                    "error": "no fleet attached — rollout needs the fleet "
                             "supervisor (serve/fleet.py)"})
                return
            path = data.get("path")
            if not path:
                self._reply_json(400,
                                 {"error": "rollout needs {'path': ...}"})
                return
            res = router._rollout_cb("start", data)
            self._reply_json(200 if res.get("ok") else 409, res)

        def _scale(self, data: dict) -> None:
            if router._scale_cb is None:
                self._reply_json(409, {
                    "error": "no fleet attached — scaling needs the fleet "
                             "supervisor (serve/fleet.py)"})
                return
            n = data.get("replicas")
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                self._reply_json(400, {
                    "error": "scale needs {'replicas': <int >= 1>}"})
                return
            res = router._scale_cb("set", data)
            self._reply_json(200 if res.get("ok") else 409, res)

    return RouterHandler


# ------------------------------------------------------------------ CLI

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.serve route",
        description="front router for a serving fleet "
                    "(docs/serving.md, 'Fleet')")
    p.add_argument("--fleet", metavar="PATH",
                   help="fleet.json — spawn + supervise replicas AND "
                        "route (serve/fleet.py)")
    p.add_argument("--workdir", default=None, metavar="DIR",
                   help="fleet workdir (port files / replica logs; "
                        "--fleet only)")
    p.add_argument("--replicas", metavar="SPEC",
                   help="route over replicas managed elsewhere: "
                        "name=host:port[,name=host:port...]")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8400,
                   help="0 picks an ephemeral port (see --port-file)")
    p.add_argument("--retry-budget", type=int, default=2,
                   help="extra attempts per request, each on a replica "
                        "not yet tried (docs/serving.md)")
    p.add_argument("--hedge", action="store_true",
                   help="duplicate requests that outlive the observed "
                        "upstream p99 onto a second replica; first "
                        "answer wins")
    p.add_argument("--upstream-timeout", type=float, default=10.0)
    p.add_argument("--poll-interval", type=float, default=0.25)
    p.add_argument("--breaker-failures", type=int, default=3)
    p.add_argument("--breaker-open-s", type=float, default=1.0)
    p.add_argument("--autoscale", action="store_true",
                   help="with --fleet: embed the autoscaler loop "
                        "(obs/agg/autoscale.py) in the supervisor; "
                        "needs fleet.json's autoscale block with "
                        "'store' and 'capacity'")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="atomically write {host,port,pid} JSON once bound")
    p.add_argument("--run-dir", default=None, metavar="DIR",
                   help="flush tail-sampled trace segments to "
                        "DIR/traces.jsonl (docs/observability.md "
                        "'Distributed tracing')")
    return p


def parse_replica_spec(spec: str) -> list[tuple[str, str]]:
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, addr = part.partition("=")
        if not eq or not addr:
            raise ValueError(
                f"bad replica spec {part!r} (want name=host:port)")
        out.append((name, addr))
    if not out:
        raise ValueError("empty --replicas spec")
    return out


def run_router(args, replicas: list[tuple[str, str]],
               rollout_cb=None) -> Router:
    router = Router(
        replicas, host=args.host, port=args.port,
        retry_budget=args.retry_budget, hedge=args.hedge,
        upstream_timeout_s=args.upstream_timeout,
        poll_interval_s=args.poll_interval,
        breaker_failures=args.breaker_failures,
        breaker_open_s=args.breaker_open_s,
        rollout_cb=rollout_cb,
        run_dir=getattr(args, "run_dir", None),
    )
    router.start_background()
    return router


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if bool(args.fleet) == bool(args.replicas):
        print("route: pass exactly one of --fleet / --replicas",
              file=sys.stderr)
        return 2
    if args.fleet:
        # the fleet supervisor owns the full lifecycle (spawn replicas,
        # run this router in-process, drive rollouts)
        if __package__:
            from .fleet import main as fleet_main
        else:
            fleet = _load("_estorch_serve_fleet", "fleet.py")
            fleet_main = fleet.main
        fleet_argv = ["--fleet", args.fleet, "--host", args.host]
        if args.port != 8400:
            fleet_argv += ["--port", str(args.port)]
        if args.port_file:
            fleet_argv += ["--port-file", args.port_file]
        if args.workdir:
            fleet_argv += ["--workdir", args.workdir]
        if args.autoscale:
            fleet_argv += ["--autoscale"]
        return fleet_main(fleet_argv)
    if args.autoscale:
        # replicas managed elsewhere: nothing to spawn or retire
        print("route: --autoscale needs --fleet (a supervisor that "
              "owns the replica lifecycle)", file=sys.stderr)
        return 2
    try:
        replicas = parse_replica_spec(args.replicas)
    except ValueError as e:
        print(f"route: {e}", file=sys.stderr)
        return 2
    router = run_router(args, replicas)
    stop = threading.Event()

    def _on_signal(signum, frame):
        del frame
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(json.dumps({
        "ready": True, "role": "router",
        "url": f"http://{router.host}:{router.port}",
        "pid": os.getpid(),
        "replicas": [r.name for r in router.replicas()],
    }), flush=True)
    if args.port_file:
        write_port_file(args.port_file, router.host, router.port)
    while not stop.wait(0.5):
        pass
    final = router.shutdown(drain=True)
    print(json.dumps(final, default=float), flush=True)
    return 0 if final["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
