"""Versioned policy bundles — the deployable artifact of a training run.

A bundle is a self-describing directory that carries everything needed to
serve a trained policy in a FRESH process, with a bit-exactness contract:
``Bundle.predict(obs)`` equals the exporting run's ``ES.predict(obs)``
(same host compute configuration; docs/serving.md).  Contents:

- ``arrays.npz``   — params_flat (the center or best-member vector),
                     every frozen collection's leaves (VBN reference
                     stats, …), and the running obs-normalization triple
                     when the run trained with ``obs_norm``;
- ``MANIFEST.json``— schema + bundle version, the module import spec
                     (``"pkg.mod:Class"`` + JSON kwargs) that rebuilds
                     the flax policy, obs shape, provenance (algorithm,
                     backend, generation, best reward), the runtime
                     facts a regression hunt needs (git sha, jax/numpy
                     versions — reusing obs/manifest.py), and the
                     sha256 of ``arrays.npz``.

Write protocol (the checkpoint lesson, utils/checkpoint.py): payload
first, ``MANIFEST.json`` LAST via atomic rename — the manifest IS the
commit point.  A crash at any earlier moment leaves a directory
``load_bundle`` rejects as uncommitted, never a loadable-looking bundle
with a half-written payload.  Re-exporting over an existing bundle
deletes the manifest first (decommit) for the same reason.

Host-backend (torch) policies are not bundleable — torch has its own
serialization story and the serving stack is JAX-native; ``export_bundle``
says so instead of writing an artifact the server cannot run.
"""

from __future__ import annotations

import dataclasses
import hashlib
import importlib
import json
import os
import time
from typing import Any

import numpy as np

BUNDLE_SCHEMA = 1
MANIFEST_NAME = "MANIFEST.json"
ARRAYS_NAME = "arrays.npz"
WARM_DIR = "warm"  # packed XLA-cache entries (serve/warm.py)


class BundleError(ValueError):
    """Malformed, corrupt, or incompatible bundle."""


# --------------------------------------------------------------------- util

def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _resolve_import(spec: str):
    """``"pkg.mod:attr"`` → the attribute (class/function)."""
    mod, _, attr = spec.partition(":")
    if not attr:
        raise BundleError(f"import spec {spec!r} must be 'module:attr'")
    try:
        obj = importlib.import_module(mod)
    except ImportError as e:
        raise BundleError(
            f"bundle module {spec!r} is not importable in this process: {e}"
        ) from e
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _import_path(obj) -> str:
    mod = getattr(obj, "__module__", None)
    qual = getattr(obj, "__qualname__", None)
    if not mod or not qual or "<locals>" in qual:
        raise BundleError(
            f"{obj!r} has no stable import path — bundles must reference "
            "module-level classes/functions so a fresh serving process can "
            "import them"
        )
    if mod == "__main__":
        raise BundleError(
            f"{obj!r} is defined in __main__ — move it to an importable "
            "module (the serving process cannot import your script's "
            "__main__) or pass module_import/module_kwargs explicitly"
        )
    return f"{mod}:{qual}"


_JSON_SCALARS = (bool, int, float, str, type(None))


def _encode_field(name: str, v):
    """A module dataclass field value → JSON, or raise with guidance."""
    if isinstance(v, _JSON_SCALARS):
        return v
    if isinstance(v, (tuple, list)):
        out = []
        for x in v:
            if not isinstance(x, _JSON_SCALARS):
                raise BundleError(
                    f"module field {name!r} contains non-JSON element {x!r}; "
                    "pass module_kwargs explicitly to export_bundle"
                )
            out.append(x)
        return out
    if callable(v):
        path = _import_path(v)
        if _resolve_import(path) is not v:
            raise BundleError(
                f"module field {name!r}={v!r} does not round-trip through "
                f"its import path {path!r}; pass module_kwargs explicitly"
            )
        return {"__callable__": path}
    raise BundleError(
        f"module field {name!r}={v!r} is not JSON-serializable; pass "
        "module_kwargs explicitly to export_bundle"
    )


def _decode_field(v):
    if isinstance(v, dict) and "__callable__" in v:
        return _resolve_import(v["__callable__"])
    return v


def _eq_default(v, default) -> bool:
    try:
        return bool(v == default)
    except Exception:  # exotic __eq__: treat as non-default, encode it
        pass
    return False


def _module_spec(module) -> tuple[str, dict]:
    """(import path, JSON kwargs) that reconstruct a flax module.

    flax ``nn.Module``s are dataclasses — fields at their class default
    are omitted (the class reconstructs them, including non-serializable
    defaults like activation callables); the rest must encode to JSON.
    """
    cls = type(module)
    path = _import_path(cls)
    if _resolve_import(path) is not cls:
        raise BundleError(
            f"policy class {cls.__name__} does not round-trip through its "
            f"import path {path!r}; pass module_import/module_kwargs "
            "explicitly"
        )
    kwargs = {}
    for f in dataclasses.fields(module):
        if f.name in ("parent", "name"):
            continue  # flax wiring, not construction config
        v = getattr(module, f.name)
        if v is f.default:
            continue
        if f.default is not dataclasses.MISSING and _eq_default(v, f.default):
            continue
        kwargs[f.name] = _encode_field(f.name, v)
    return path, kwargs


def _flatten_collection(tree) -> tuple[list[np.ndarray], Any]:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [np.asarray(x) for x in leaves], treedef


# ------------------------------------------------------------------- export

def export_bundle(
    es,
    path: str,
    *,
    use_best: bool = False,
    version: str | int | None = None,
    module_import: str | None = None,
    module_kwargs: dict | None = None,
    extra: dict | None = None,
    warm: bool = False,
    warm_max_batch: int = 32,
    serve_bf16: bool = False,
) -> str:
    """Export a trained ``ES`` (device/pooled backend) into a bundle dir.

    ``use_best`` exports the best-ever member snapshot instead of the
    current center.  ``version`` tags the artifact (default: the source
    generation).  ``module_import``/``module_kwargs`` override the
    automatic module spec for policies whose config fields don't encode
    to JSON.  Returns the absolute bundle path.

    ``warm=True`` additionally packs the serving programs' compiled XLA
    executables into the bundle (``warm/`` + manifest ``warm`` block,
    serve/warm.py): the export process replays the serve-time load for a
    ``warm_max_batch`` bucket ladder under a scoped compilation-cache
    redirect, paying the JIT storm ONCE so every replica that loads the
    bundle serves its first request without a fresh XLA build.

    ``serve_bf16=True`` opts the bundle into the quantized serving fast
    path (manifest ``serve_dtypes``) — the exporter's assertion that
    accuracy-bounded bf16 answers are acceptable for this policy.  A
    server started with ``--dtype bf16`` refuses bundles that did not
    opt in.  Combined with ``warm=True`` the bf16 ladder is warmed too,
    and a policy whose measured divergence exceeds the documented bound
    fails the export with the diagnosis instead of shipping a bundle
    every server will refuse.
    """
    if getattr(es, "backend", None) == "host":
        raise NotImplementedError(
            "host-backend (torch) policies are not bundleable — the serving "
            "stack is JAX-native; use torch.save on es.policy.state_dict() "
            "for torch deployment"
        )
    if es.module is None:
        raise BundleError("this ES has no flax module to bundle")

    if use_best and es._best_flat is None:
        raise BundleError(
            "use_best=True but no best-member snapshot exists yet — "
            "train at least one generation first"
        )
    flat = np.asarray(
        es._best_flat if use_best else es.state.params_flat, np.float32
    )

    if module_import is None:
        module_import, auto_kwargs = _module_spec(es.module)
        if module_kwargs is None:
            module_kwargs = auto_kwargs
    elif module_kwargs is None:
        module_kwargs = {}

    arrays: dict[str, np.ndarray] = {"params_flat": flat}
    frozen_meta: dict[str, int] = {}
    for coll, tree in sorted(es._frozen.items()):
        leaves, _ = _flatten_collection(tree)
        frozen_meta[coll] = len(leaves)
        for i, leaf in enumerate(leaves):
            arrays[f"frozen.{coll}.{i}"] = leaf

    obs_norm = bool(getattr(es, "_obs_norm", False))
    if obs_norm:
        cnt, mean, m2 = es.state.obs_stats
        arrays["obs_stats.count"] = np.asarray(cnt)
        arrays["obs_stats.mean"] = np.asarray(mean)
        arrays["obs_stats.m2"] = np.asarray(m2)

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    manifest_path = os.path.join(path, MANIFEST_NAME)
    if os.path.exists(manifest_path):
        # decommit BEFORE touching the payload: a reader racing this
        # re-export sees "uncommitted", never a manifest whose checksum
        # describes the previous payload
        os.remove(manifest_path)
    arrays_path = os.path.join(path, ARRAYS_NAME)
    with open(arrays_path, "wb") as f:
        np.savez(f, **arrays)

    from ..obs.manifest import collect_manifest

    mesh = getattr(es, "mesh", None)
    runtime = collect_manifest(
        devices=list(mesh.devices.flat) if mesh is not None else None
    )
    manifest = {
        "schema": BUNDLE_SCHEMA,
        "created_unix": time.time(),
        "version": str(version if version is not None else es.generation),
        "module": {"import": module_import, "kwargs": module_kwargs},
        "obs_shape": [int(d) for d in np.shape(es._obs0)],
        "param_dim": int(flat.shape[0]),
        "recurrent": bool(getattr(es, "_recurrent", False)),
        "serve_dtypes": ["f32"] + (["bf16"] if serve_bf16 else []),
        "obs_norm": obs_norm,
        "obs_clip": float(getattr(es, "_obs_clip", 5.0)),
        "frozen": frozen_meta,
        "source": {
            "algorithm": type(es).__name__,
            "backend": es.backend,
            "generation": int(es.generation),
            "population_size": int(es.population_size),
            "sigma": float(es.sigma),
            "seed": int(es.seed),
            "best_reward": float(es.best_reward),
            "use_best": bool(use_best),
        },
        "runtime": runtime,
        "sha256": {ARRAYS_NAME: _sha256_file(arrays_path)},
    }
    if getattr(es, "_scenarios", None) is not None:
        # the bundle names the scenarios its policy was trained under:
        # the distribution spec + draw seed reproduce every variant's
        # constants exactly (estorch_tpu/scenarios, docs/scenarios.md)
        manifest["source"]["scenarios"] = es._scenarios.spec_json()
    if extra:
        manifest["extra"] = extra
    _commit_manifest(path, manifest)
    if warm:
        from .warm import warm_bundle

        # warm against the COMMITTED bundle (the replay loads it through
        # the real load path), then re-commit the manifest with the warm
        # block + checksums — a crash mid-warm leaves a valid cold bundle
        warm_block, warm_shas = warm_bundle(
            path, max_batch=warm_max_batch,
            dtypes=manifest["serve_dtypes"])
        manifest["warm"] = warm_block
        manifest["sha256"].update(warm_shas)
        # no decommit here: nothing between the two commits mutates the
        # payload (unlike a re-export), and os.replace swaps atomically —
        # a reader sees either the valid cold manifest or the warm one
        _commit_manifest(path, manifest)
    else:
        # a re-export without warmth must not leave the PREVIOUS export's
        # warm entries beside a manifest that no longer references them
        import shutil

        shutil.rmtree(os.path.join(path, WARM_DIR), ignore_errors=True)
    return path


def _commit_manifest(path: str, manifest: dict) -> None:
    manifest_path = os.path.join(path, MANIFEST_NAME)
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, default=float)
    os.replace(tmp, manifest_path)  # the commit point


# ----------------------------------------------------------------- validate

def validate_bundle(path: str) -> dict:
    """Structural validation WITHOUT importing jax or the policy module —
    what :func:`estorch_tpu.doctor.check_serve` runs.  Returns the
    manifest; raises :class:`BundleError` with the finding otherwise.
    """
    path = os.path.abspath(path)
    manifest_path = os.path.join(path, MANIFEST_NAME)
    if not os.path.isdir(path):
        raise BundleError(f"bundle path {path!r} is not a directory")
    if not os.path.exists(manifest_path):
        raise BundleError(
            f"bundle at {path!r} has no {MANIFEST_NAME} — the export never "
            "committed (crashed mid-write?) or this is not a bundle"
        )
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except ValueError as e:
        raise BundleError(f"unreadable {MANIFEST_NAME}: {e}") from e
    schema = manifest.get("schema")
    if schema != BUNDLE_SCHEMA:
        raise BundleError(
            f"bundle schema {schema!r} != supported {BUNDLE_SCHEMA} — "
            "re-export from the run that produced it"
        )
    for key in ("module", "obs_shape", "param_dim", "sha256", "version"):
        if key not in manifest:
            raise BundleError(f"{MANIFEST_NAME} is missing {key!r}")
    arrays_path = os.path.join(path, ARRAYS_NAME)
    if not os.path.exists(arrays_path):
        raise BundleError(f"bundle is missing its payload {ARRAYS_NAME}")
    sha = manifest.get("sha256")
    want = sha.get(ARRAYS_NAME) if isinstance(sha, dict) else None
    if not want:
        raise BundleError(
            f"{MANIFEST_NAME} records no checksum for {ARRAYS_NAME} — "
            "not a bundle this version can trust"
        )
    # EVERY checksummed file is verified — the warm payload is part of
    # the artifact and gets the same integrity contract as arrays.npz
    for rel, want in sorted(sha.items()):
        fpath = os.path.join(path, *rel.split("/"))
        if not os.path.exists(fpath):
            raise BundleError(
                f"bundle is missing checksummed file {rel!r}")
        got = _sha256_file(fpath)
        if got != want:
            raise BundleError(
                f"{rel} checksum mismatch (manifest {str(want)[:12]}…, "
                f"file {got[:12]}…) — the payload is corrupt or was "
                "modified after export"
            )
    _validate_warm_block(path, manifest)
    with np.load(arrays_path) as z:
        if "params_flat" not in z.files:
            raise BundleError(f"{ARRAYS_NAME} has no params_flat array")
        n = int(z["params_flat"].shape[0])
    if n != int(manifest["param_dim"]):
        raise BundleError(
            f"params_flat has {n} parameters but the manifest promises "
            f"{manifest['param_dim']}"
        )
    return manifest


def _validate_warm_block(path: str, manifest: dict) -> None:
    """Structural validation of the packed warmth (jax-free — doctor's
    warm probe runs this on wedged-runtime machines): the warm block must
    name a known format, every entry must be checksummed AND present
    (checksum bytes verified by the caller's sha loop), and the bucket
    ladder must be COMPLETE — warmed + verification-excluded buckets
    covering exactly the ladder of its recorded ``max_batch``, so a
    served shape can't silently fall outside the warmth.  Version or
    platform mismatch is NOT an error here — the bundle is valid, the
    warmth just won't hit; ``serve/warm.py::install_warmth`` (and the
    doctor) reports that as a finding."""
    warm = manifest.get("warm")
    if warm is None:
        return
    if not isinstance(warm, dict):
        raise BundleError("manifest 'warm' block is not an object")
    if warm.get("format") != "xla_cache":
        raise BundleError(
            f"warm block has unknown format {warm.get('format')!r} — "
            "this version packs only 'xla_cache'")
    for key in ("max_batch", "entries", "jax_version", "platform"):
        if key not in warm:
            raise BundleError(f"warm block is missing {key!r}")
    entries = warm["entries"]
    if not isinstance(entries, dict) or not entries:
        raise BundleError("warm block packs no cache entries")
    sha = manifest.get("sha256") or {}
    for fname in entries:
        rel = f"{WARM_DIR}/{fname}"
        if rel not in sha:
            raise BundleError(
                f"warm entry {fname!r} has no checksum in the manifest — "
                "the warmth cannot be trusted")
    if not bool(warm.get("recurrent_only")):
        try:
            from .batcher import bucket_sizes

            ladder = set(bucket_sizes(int(warm["max_batch"])))
        except ValueError as e:
            raise BundleError(f"warm block max_batch invalid: {e}") from e
        covered = set(int(b) for b in warm.get("buckets", [])) | set(
            int(b) for b in warm.get("buckets_excluded", []))
        if covered != ladder:
            raise BundleError(
                f"warm block ladder incomplete: covers {sorted(covered)} "
                f"but max_batch {warm['max_batch']} needs {sorted(ladder)}")


# --------------------------------------------------------------------- load

class Bundle:
    """A loaded policy bundle: rebuilt module + parameters + jitted
    predict, honoring the exporting run's predict contract."""

    def __init__(self, path: str, manifest: dict, module, params,
                 frozen: dict, obs_stats):
        self.path = path
        self.manifest = manifest
        self.module = module
        self.params = params
        self.frozen = frozen
        self.obs_stats = obs_stats  # (count, mean, m2) or None
        self.version = manifest["version"]
        self.recurrent = bool(manifest.get("recurrent", False))
        self.obs_shape = tuple(manifest["obs_shape"])
        self.obs_clip = float(manifest.get("obs_clip", 5.0))
        self._obs_norm = bool(manifest.get("obs_norm", False))
        # dtypes the EXPORTER opted this policy into serving with (old
        # bundles predate the key: f32 only)
        self.serve_dtypes = tuple(manifest.get("serve_dtypes") or ("f32",))
        # packed warmth facts (serve/warm.py) — None on cold bundles;
        # install status is recorded by load_bundle(install_warm=True)
        self.warm_info = manifest.get("warm")
        self.warm_status: dict | None = None
        self._params_cast: dict = {}

        frozen_d = frozen

        if self.recurrent:

            def policy_apply(p, obs, h):
                return module.apply({"params": p, **frozen_d}, obs, h)

        else:

            def policy_apply(p, obs):
                return module.apply({"params": p, **frozen_d}, obs)

        self._policy_apply = policy_apply
        from .predictor import make_single_predict

        self._predict_fn = make_single_predict(
            policy_apply, recurrent=self.recurrent,
            obs_norm=self._obs_norm, obs_clip=self.obs_clip,
        )

    # ---------------------------------------------------------- predict

    def predict(self, obs, carry=None):
        """Forward pass, bit-equal to the exporting run's ``ES.predict``
        (same host compute configuration).  Recurrent bundles return
        ``(out, new_carry)``; ``carry=None`` starts an episode."""
        import jax.numpy as jnp

        obs = jnp.asarray(obs)
        if self.recurrent:
            if carry is None:
                from ..envs.rollout import carry_init_takes_params

                ci = self.module.carry_init
                carry = ci(self.params) if carry_init_takes_params(ci) else ci()
            return self._predict_fn(self.params, self.obs_stats, obs, carry)
        return self._predict_fn(self.params, self.obs_stats, obs)

    def _params_for(self, dtype: str):
        """Param tree for a serving dtype — the quantized cast happens
        ONCE here (the engine's once-per-member discipline), never inside
        the jitted forward."""
        if dtype == "f32":
            return self.params
        if dtype not in self._params_cast:
            import jax.numpy as jnp

            from ..parallel.engine import _cast_leaves

            self._params_cast[dtype] = _cast_leaves(self.params,
                                                    jnp.bfloat16)
        return self._params_cast[dtype]

    def batched_predict_fn(self, dtype: str = "f32"):
        """``f(obs_batch (B, *obs_shape) np.ndarray) -> np.ndarray`` — the
        dynamic batcher's compute, one XLA compile per batch shape.
        Stateless policies only (the server's contract).

        ``dtype="bf16"`` returns the quantized fast path (engine shim,
        half the weight bytes streamed per batch) — refused with
        :class:`BundleError` unless the bundle opted in at export
        (``serve_dtypes``): quantized answers are an accuracy decision
        the exporter makes, never a silent server-side downgrade."""
        if self.recurrent:
            raise BundleError(
                "recurrent bundles cannot serve through the dynamic "
                "batcher — the hidden carry belongs to a session, and the "
                "batcher coalesces unrelated requests; use predict(obs, "
                "carry) in-process"
            )
        if dtype != "f32" and dtype not in self.serve_dtypes:
            raise BundleError(
                f"bundle at {self.path!r} did not opt into {dtype} "
                f"serving (serve_dtypes={list(self.serve_dtypes)}) — "
                "re-export with export_bundle(..., serve_bf16=True) to "
                "assert the quantized path is acceptable for this policy"
            )
        import jax.numpy as jnp

        from .predictor import make_batched_predict

        fn = make_batched_predict(
            self._policy_apply, obs_norm=self._obs_norm,
            obs_clip=self.obs_clip, dtype=dtype,
        )
        params, stats = self._params_for(dtype), self.obs_stats

        def batch_predict(obs_batch: np.ndarray) -> np.ndarray:
            return np.asarray(fn(params, stats, jnp.asarray(obs_batch)))

        return batch_predict


def load_bundle(path: str, install_warm: bool = False) -> Bundle:
    """Validate + load a bundle; raises :class:`BundleError` on any
    structural, checksum, or module-compatibility problem.

    ``install_warm=True`` installs the bundle's packed warmth (compiled
    XLA programs, serve/warm.py) into this process's compilation cache
    BEFORE any jax work — the serving fast path.  Incompatible warmth
    (different jax version/platform) is skipped with the reason recorded
    in ``bundle.warm_status``, never an error."""
    manifest = validate_bundle(path)
    path = os.path.abspath(path)

    warm_status = None
    if install_warm:
        from .warm import install_warmth

        # BEFORE the first jax compile below: the module re-init and
        # param unravel are themselves programs the warmth covers
        warm_status = install_warmth(path, manifest)

    import jax
    import jax.numpy as jnp

    from ..ops.params import make_param_spec

    module_cls = _resolve_import(manifest["module"]["import"])
    kwargs = {k: _decode_field(v)
              for k, v in manifest["module"]["kwargs"].items()}
    try:
        module = module_cls(**kwargs)
    except TypeError as e:
        raise BundleError(
            f"policy class {manifest['module']['import']!r} rejected the "
            f"bundled kwargs {sorted(kwargs)}: {e} — the class signature "
            "changed since export"
        ) from e

    obs0 = jnp.zeros(tuple(manifest["obs_shape"]), jnp.float32)
    recurrent = bool(manifest.get("recurrent", False))
    # structure-only init, mirroring ES._module_init: shapes depend on the
    # obs shape and module config, never on the key or obs values
    if recurrent:
        variables = module.init(jax.random.PRNGKey(0), obs0,
                                module.carry_init())
    else:
        variables = module.init(jax.random.PRNGKey(0), obs0)

    _, spec = make_param_spec(variables["params"])
    if spec.dim != int(manifest["param_dim"]):
        raise BundleError(
            f"rebuilt module has {spec.dim} parameters but the bundle "
            f"carries {manifest['param_dim']} — the module definition "
            "changed since export"
        )

    with np.load(os.path.join(path, ARRAYS_NAME)) as z:
        arrays = {k: z[k] for k in z.files}

    params = spec.unravel(jnp.asarray(arrays["params_flat"]))

    frozen: dict[str, Any] = {}
    for coll, n_leaves in (manifest.get("frozen") or {}).items():
        tmpl = variables.get(coll)
        if tmpl is None:
            raise BundleError(
                f"bundle carries frozen collection {coll!r} but the rebuilt "
                "module does not define it — module definition drift"
            )
        leaves, treedef = jax.tree_util.tree_flatten(tmpl)
        if len(leaves) != int(n_leaves):
            raise BundleError(
                f"frozen collection {coll!r}: module wants {len(leaves)} "
                f"leaves, bundle has {n_leaves}"
            )
        loaded = [jnp.asarray(arrays[f"frozen.{coll}.{i}"])
                  for i in range(int(n_leaves))]
        frozen[coll] = jax.tree_util.tree_unflatten(treedef, loaded)

    obs_stats = None
    if manifest.get("obs_norm"):
        obs_stats = (
            jnp.asarray(arrays["obs_stats.count"]),
            jnp.asarray(arrays["obs_stats.mean"]),
            jnp.asarray(arrays["obs_stats.m2"]),
        )

    bundle = Bundle(path, manifest, module, params, frozen, obs_stats)
    bundle.warm_status = warm_status
    return bundle
