"""The serving forward pass — ONE definition shared by ``ES.predict``,
:mod:`estorch_tpu.serve.bundle`, and the inference server.

Bit-exactness is the whole point of this module.  A served response must
equal what the exporting run's ``ES.predict`` computes, and that only
holds if every consumer builds the SAME jitted program from the SAME
closure shape (normalize → apply, params and running stats as arguments).
Two independently-written predict paths would drift — eager vs jitted
and GEMV vs GEMM execution families genuinely differ in final bits on
CPU (docs/serving.md "Bit-exactness contract") — so the builders live
here and everyone imports them.

Execution families (measured, tests/test_serve.py pins them):

* single-observation calls lower to GEMV; ``jit`` and eager agree bit-
  for-bit at batch 1;
* batched calls (B ≥ 2) lower to GEMM; rows are bit-identical across
  batch sizes *within the jitted family*, which is why the dynamic
  batcher pads to power-of-two buckets of at least 2 — a request's bits
  must not depend on how many neighbors it was coalesced with;
* bit-parity across *processes* additionally requires the same host
  compute configuration (e.g. ``--cpu-devices`` on the server matching
  the exporting run's virtual-device count).
"""

from __future__ import annotations

from typing import Any, Callable

import jax

SERVE_DTYPES = ("f32", "bf16")


def _apply_for_dtype(policy_apply: Callable[..., Any], dtype: str,
                     recurrent: bool = False) -> Callable[..., Any]:
    """The engine's bf16 I/O shim applied to a serving forward pass.

    ``dtype="bf16"`` reuses ``parallel/engine.py``'s compute-dtype
    machinery (obs cast in, output cast back to f32, params must ALREADY
    be bf16 — cast once where they are built, ``Bundle._params_for``),
    so the served quantized program is the same family the bf16 training
    path runs.  Normalization composes OUTSIDE the shim exactly like the
    engine: raw observations are normalized in f32, then cast.
    """
    if dtype not in SERVE_DTYPES:
        raise ValueError(
            f"serving dtype must be one of {SERVE_DTYPES}, got {dtype!r}")
    if dtype == "f32":
        return policy_apply
    from ..parallel.engine import _bf16_io_apply, _bf16_io_apply_stateful

    if recurrent:
        return _bf16_io_apply_stateful(policy_apply)
    return _bf16_io_apply(policy_apply)


def make_single_predict(
    policy_apply: Callable[..., Any],
    *,
    recurrent: bool = False,
    obs_norm: bool = False,
    obs_clip: float = 5.0,
    dtype: str = "f32",
) -> Callable[..., Any]:
    """Jitted ``f(params, obs_stats, obs[, carry])`` for one observation.

    ``obs_stats`` is the (count, mean, m2) Welford triple when
    ``obs_norm`` (normalization happens INSIDE the jitted program so the
    composition matches the rollout path), and must be passed as ``None``
    otherwise.  Recurrent policies take and return the hidden carry:
    ``f(...) -> (out, new_carry)``.

    Also correct for batched ``obs`` (leading batch axis): flax modules
    broadcast over leading dims, and normalization is elementwise — the
    jitted batch call lands in the same GEMM family as
    :func:`make_batched_predict`'s rows.

    ``dtype="bf16"`` builds the quantized program (engine shim, see
    :func:`_apply_for_dtype`); params must already be bf16.
    """
    policy_apply = _apply_for_dtype(policy_apply, dtype,
                                    recurrent=recurrent)
    if obs_norm:
        from ..parallel.engine import normalize_obs

        if recurrent:

            def f(params, stats, obs, carry):
                return policy_apply(
                    params, normalize_obs(obs, stats, obs_clip), carry
                )

        else:

            def f(params, stats, obs):
                return policy_apply(params, normalize_obs(obs, stats, obs_clip))

    else:
        if recurrent:

            def f(params, stats, obs, carry):
                del stats
                return policy_apply(params, obs, carry)

        else:

            def f(params, stats, obs):
                del stats
                return policy_apply(params, obs)

    return jax.jit(f)


def make_batched_predict(
    policy_apply: Callable[..., Any],
    *,
    obs_norm: bool = False,
    obs_clip: float = 5.0,
    dtype: str = "f32",
) -> Callable[..., Any]:
    """Jitted ``f(params, obs_stats, obs_batch (B, *obs_shape)) -> (B, ...)``
    — the dynamic batcher's program, one XLA compile per batch shape.

    Stateless policies only: a recurrent policy's carry belongs to a
    session, and the batcher coalesces *unrelated* requests — the server
    refuses recurrent bundles rather than silently mixing carries.

    ``dtype="bf16"`` builds the quantized fast path (engine shim; params
    must already be bf16).  Its accuracy vs the f32 program is MEASURED
    per bucket at load (``serve/batcher.py::measure_quant_divergence``),
    never assumed — see docs/serving.md "Cold start & quantized serving".
    """
    policy_apply = _apply_for_dtype(policy_apply, dtype)
    if obs_norm:
        from ..parallel.engine import normalize_obs

        def one(params, stats, obs):
            return policy_apply(params, normalize_obs(obs, stats, obs_clip))

    else:

        def one(params, stats, obs):
            del stats
            return policy_apply(params, obs)

    return jax.jit(jax.vmap(one, in_axes=(None, None, 0)))
