"""Fleet supervisor: N serving replicas + the front router + canary
rollout (docs/serving.md "Fleet").

``python -m estorch_tpu.serve route --fleet fleet.json`` spawns N
replica processes from one bundle (each a full ``python -m
estorch_tpu.serve`` server: heartbeat, warm load, SIGTERM drain — the
same child the PR-3 watchdog babysits), runs the front router
(serve/router.py) in-process over them, respawns dead replicas with
exponential backoff, escalates wedged ones (alive process, silent
socket) to SIGKILL + respawn, and drives canary rollout:

``POST /rollout {"path": <bundle>}`` on the router →

1. **canary** — ONE replica is quarantined out of live rotation FIRST
   (a client must never see an unpromoted bundle's answers), then
   hot-reloads the new bundle (the atomic ``/reload`` swap; a bundle
   that fails to load aborts here, the fleet never left the incumbent);
2. **shadow** — the router duplicates a configured fraction of live
   traffic off-path as PAIRED probes (canary + a live incumbent
   through the identical path), collecting latency samples and
   (request, live answer, canary answer) parity triples;
3. **gate** — promote ONLY if (a) the canary's ``/predict`` latency
   quantile stays inside the ``obs regress --tail`` learned band vs the
   incumbent samples from the same window, and (b) the bit-parity spot
   check passes: the same observation rows answered through canary and
   incumbent compare EXACTLY (rollouts ship re-exports / serving-config
   changes of the same parameters; a perturbed or corrupted bundle
   fails here — pass ``"check_parity": false`` for an intentional
   policy change);
4. **promote** — the remaining replicas ``/reload`` to the new bundle;
   **abort** — the canary reloads back to the incumbent (or, if even
   that fails, is killed and respawned on the incumbent — the respawn
   path IS the rollback of last resort), and the structured
   ``rollout_aborted`` result carries the tail-band or parity evidence.

Serving chaos is declared like training chaos: ``ESTORCH_CHAOS``
``kill_replica``/``wedge_replica`` events (wall-clock ``at_s``, same
once-semantics ledger — resilience/chaos.py) are fired by the monitor
loop, so a fleet test schedules its SIGKILL instead of ad-hoc
``os.kill``.

Scaling (docs/serving.md "Autoscaling"): ``POST /scale {"replicas": N}``
on the router is the fleet's admin surface — the autoscaler daemon
(obs/agg/autoscale.py) actuates here.  Slot ADD is a warm spawn from
the incumbent bundle, gated on ``compiles_at_load == 0`` (the PR-12
warmth proof, recorded per slot).  Slot REMOVE is drain-then-retire:
the router deselects the least-loaded replica FIRST, in-flight answers
complete, THEN the replica gets SIGTERM (its own drain path) — a
retirement costs zero client errors.  ``--autoscale`` embeds the
autoscaler loop in this supervisor (fleet.json ``autoscale`` block:
``store``, ``capacity``, policy knobs).

Stdlib-only, jax-free, file-runnable (``python
estorch_tpu/serve/fleet.py``): replicas are subprocesses that pay the
jax import; the supervisor that must outlive them never does.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time

if __package__:
    from ..obs.export.regress import compare_tail
    from ..resilience import chaos as _chaos
    from .router import Router, write_port_file
else:  # file-run (wedged-jax host): load siblings without any package init
    import importlib.util

    def _load(name: str, *rel: str):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            *rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _regress = _load("_estorch_obs_regress", os.pardir, "obs", "export",
                     "regress.py")
    _chaos = _load("_estorch_resilience_chaos", os.pardir, "resilience",
                   "chaos.py")
    _router_mod = _load("_estorch_serve_router", "router.py")
    compare_tail = _regress.compare_tail
    Router = _router_mod.Router
    write_port_file = _router_mod.write_port_file

FLEET_SCHEMA = 1
START_TIMEOUT_S = 180.0
# scale-down: bound on waiting for router-side in-flight to a retiring
# replica to reach zero, and on the SIGTERMed replica's own drain
# (server.py DRAIN_GRACE_S=15 + margin)
RETIRE_INFLIGHT_WAIT_S = 20.0
RETIRE_REAP_S = 25.0

ROLLOUT_DEFAULTS = {
    "shadow_fraction": 0.5,
    "min_shadow": 24,
    "parity_samples": 8,
    "window_s": 30.0,
    "tail_quantile": 0.99,
    "min_band_pct": 5.0,
    "check_parity": True,
}


class FleetError(RuntimeError):
    """Bad fleet.json or an unrecoverable supervision failure."""


def validate_fleet_config(obj) -> list[str]:
    """Structural problems of a parsed fleet file ([] when clean)."""
    if not isinstance(obj, dict) or obj.get("schema") != FLEET_SCHEMA:
        return [f"fleet file must be an object with schema={FLEET_SCHEMA}"]
    problems = []
    if not obj.get("bundle") or not isinstance(obj["bundle"], str):
        problems.append("bundle: required (path to an exported bundle)")
    n = obj.get("replicas")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        problems.append("replicas: required, integer >= 1")
    for section in ("serve", "router", "respawn", "rollout", "autoscale"):
        if section in obj and not isinstance(obj[section], dict):
            problems.append(f"{section}: must be an object")
    az = obj.get("autoscale")
    if isinstance(az, dict):
        mn, mx = az.get("min_replicas", 1), az.get("max_replicas", 64)
        for key, v in (("min_replicas", mn), ("max_replicas", mx)):
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                problems.append(
                    f"autoscale.{key}: must be an integer >= 1")
        if (isinstance(mn, int) and isinstance(mx, int)
                and not isinstance(mn, bool) and not isinstance(mx, bool)
                and mn > mx):
            problems.append(
                "autoscale.min_replicas: must be <= max_replicas")
    ro = obj.get("rollout") or {}
    frac = ro.get("shadow_fraction",
                  ROLLOUT_DEFAULTS["shadow_fraction"])
    if not isinstance(frac, (int, float)) or not 0.0 < float(frac) <= 1.0:
        problems.append("rollout.shadow_fraction: must be in (0, 1]")
    return problems


def load_fleet_config(path: str) -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise FleetError(f"{path}: unreadable fleet file: {e}") from e
    problems = validate_fleet_config(obj)
    if problems:
        raise FleetError(f"{path}: " + "; ".join(problems))
    base = os.path.dirname(os.path.abspath(path))
    if not os.path.isabs(obj["bundle"]):
        obj["bundle"] = os.path.join(base, obj["bundle"])
    az = obj.get("autoscale")
    if isinstance(az, dict):
        for key in ("store", "capacity"):
            if isinstance(az.get(key), str) and not os.path.isabs(az[key]):
                az[key] = os.path.join(base, az[key])
    return obj


class _Slot:
    """One replica slot: the process currently (or about to be) filling
    it, plus its respawn bookkeeping.  Names are stable (``r<i>``) so
    breaker state and traces survive a respawn."""

    __slots__ = ("index", "name", "proc", "port_file", "log_path",
                 "address", "state", "started_at", "restarts",
                 "next_spawn_at", "down_since", "wedged", "cold_start")

    def __init__(self, index: int, workdir: str):
        self.index = index
        self.name = f"r{index}"
        self.proc: subprocess.Popen | None = None
        self.port_file = os.path.join(workdir, f"{self.name}_port.json")
        self.log_path = os.path.join(workdir, f"{self.name}.log")
        self.address: str | None = None
        self.state = "down"  # down | starting | up | retiring
        self.started_at = 0.0
        self.restarts = 0
        self.next_spawn_at = 0.0
        self.down_since: float | None = None
        self.wedged = False
        # last recorded /stats cold_start facts (warmth proof for the
        # INITIAL spawn and every scale-up: compiles_at_load == 0)
        self.cold_start: dict | None = None


class Fleet:
    """Supervisor-of-supervisors: replica processes + in-process router
    + the rollout state machine."""

    def __init__(self, config: dict, workdir: str, *,
                 host: str = "127.0.0.1", port: int = 0,
                 backoff_s: float = 0.5, backoff_max_s: float = 10.0,
                 start_timeout_s: float = START_TIMEOUT_S):
        self.config = dict(config)
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.bundle = os.path.abspath(config["bundle"])
        respawn = config.get("respawn") or {}
        self.backoff_s = float(respawn.get("backoff_s", backoff_s))
        self.backoff_max_s = float(respawn.get("backoff_max_s",
                                               backoff_max_s))
        self.max_restarts = int(respawn.get("max_restarts", 20))
        self.wedge_kill_s = float(respawn.get("wedge_kill_s", 5.0))
        self.start_timeout_s = float(respawn.get("start_timeout_s",
                                                 start_timeout_s))
        self.rollout_cfg = {**ROLLOUT_DEFAULTS,
                            **(config.get("rollout") or {})}
        self.autoscale_cfg = (dict(config["autoscale"])
                              if isinstance(config.get("autoscale"), dict)
                              else None)
        rc = config.get("router") or {}
        self.router = Router(
            [], host=host, port=port,
            retry_budget=int(rc.get("retry_budget", 2)),
            hedge=bool(rc.get("hedge", False)),
            hedge_min_ms=float(rc.get("hedge_min_ms", 25.0)),
            upstream_timeout_s=float(rc.get("upstream_timeout_s", 10.0)),
            poll_interval_s=float(rc.get("poll_interval_s", 0.25)),
            poll_timeout_s=float(rc.get("poll_timeout_s", 1.0)),
            breaker_failures=int(rc.get("breaker_failures", 3)),
            breaker_open_s=float(rc.get("breaker_open_s", 1.0)),
            rollout_cb=self._rollout_cb,
            scale_cb=self._scale_cb,
            # per-process trace dir (obs/tracing.py): the router's
            # sampled segments land beside the replicas' so `obs trace
            # --fleet <workdir>` assembles the whole hop chain
            run_dir=os.path.join(self.workdir, "router"),
        )
        self.slots = [_Slot(i, self.workdir)
                      for i in range(int(config["replicas"]))]
        # scaling state: slot indices only grow (a retired r2 never
        # comes back — a fresh slot gets a fresh name, so breaker and
        # log history never alias across lives)
        self._next_index = int(config["replicas"])
        self.desired = int(config["replicas"])
        self.router.desired_replicas = self.desired
        self._scale_lock = threading.Lock()  # one scale op in flight
        self._last_scale: dict | None = None
        # slot state machine fields (state/proc/timers) are written by
        # BOTH the monitor thread (_tick) and the rollout thread
        # (rollback kills) — every mutation holds this lock; process
        # kill/wait stays outside it so a slow reap can't wedge a tick
        self._slots_lock = threading.Lock()
        self.events: list[dict] = []
        self._events_lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor_thread: threading.Thread | None = None
        self._armed_mono = time.monotonic()
        # rollout state machine (one in flight; guarded by _ro_lock)
        self._ro_lock = threading.Lock()
        self._ro_state = "idle"
        self._ro_thread: threading.Thread | None = None
        self._ro_result: dict | None = None

    # -------------------------------------------------------------- events

    def _event(self, kind: str, **extra) -> None:
        with self._events_lock:
            self.events.append({"ts": time.time(), "event": kind, **extra})
            del self.events[:-500]

    def _slots_snapshot(self) -> list[_Slot]:
        """Point-in-time copy: the slot LIST is mutated by the scale
        thread (add/retire), so every iterator takes a snapshot."""
        with self._slots_lock:
            return list(self.slots)

    # -------------------------------------------------------------- spawn

    def _serve_argv(self, slot: _Slot) -> list[str]:
        sv = self.config.get("serve") or {}
        argv = [sys.executable, "-m", "estorch_tpu.serve",
                "--bundle", self.bundle, "--port", "0",
                "--port-file", slot.port_file,
                # per-slot trace dir: slot names are stable across
                # respawns, so a replica's segments survive its restarts
                "--run-dir", os.path.join(self.workdir, slot.name),
                "--beat-interval", "0.5"]
        for flag, key in (("--max-batch", "max_batch"),
                          ("--max-wait-ms", "max_wait_ms"),
                          ("--max-queue", "max_queue"),
                          ("--cpu-devices", "cpu_devices"),
                          ("--dtype", "dtype")):
            if key in sv:
                argv += [flag, str(sv[key])]
        if sv.get("no_warm"):
            argv.append("--no-warm")
        argv += [str(a) for a in sv.get("extra_args", [])]
        return argv

    def _spawn(self, slot: _Slot) -> None:
        import contextlib

        with contextlib.suppress(OSError):  # stale file from a prior life
            os.unlink(slot.port_file)
        env = {**os.environ, "ESTORCH_OBS_HEARTBEAT": os.path.join(
            self.workdir, f"{slot.name}_heartbeat.json")}
        # the child runs `-m estorch_tpu.serve`: make the package root
        # this file lives under importable regardless of the fleet's cwd
        # (a file-run fleet on an uninstalled checkout must still spawn)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        log = open(slot.log_path, "a")
        try:
            proc = subprocess.Popen(
                self._serve_argv(slot), stdout=log, stderr=log, env=env)
        finally:
            log.close()
        with self._slots_lock:
            slot.proc = proc
            slot.state = "starting"
            slot.started_at = time.monotonic()
            slot.down_since = None
            slot.wedged = False
        self._event("replica_spawned", replica=slot.name,
                    pid=proc.pid)

    def _check_starting(self, slot: _Slot) -> None:
        if os.path.exists(slot.port_file):
            try:
                with open(slot.port_file) as f:
                    pf = json.load(f)
            except (OSError, ValueError):
                return  # racing the atomic rename; next tick
            with self._slots_lock:
                slot.address = f"{pf['host']}:{pf['port']}"
                slot.state = "up"
            self.router.update_replica(slot.name, slot.address)
            self._event("replica_up", replica=slot.name,
                        address=slot.address)
            return
        if time.monotonic() - slot.started_at > self.start_timeout_s:
            self._event("replica_start_timeout", replica=slot.name)
            self._kill_slot(slot, reason="start_timeout")
            self._schedule_respawn(slot)

    def _kill_slot(self, slot: _Slot, reason: str) -> None:
        proc = slot.proc
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._event("replica_unreapable", replica=slot.name)
        with self._slots_lock:
            slot.state = "down"
            slot.down_since = None
        self._event("replica_killed", replica=slot.name, reason=reason)

    def _schedule_respawn(self, slot: _Slot) -> None:
        self.router.counters.inc("fleet_respawns_total")
        with self._slots_lock:
            slot.restarts += 1
            backoff = min(self.backoff_s * (2 ** max(0, slot.restarts - 1)),
                          self.backoff_max_s)
            slot.next_spawn_at = time.monotonic() + backoff
            slot.state = "down"

    # ------------------------------------------------------------- monitor

    def _tick(self) -> None:
        now = time.monotonic()
        slots = self._slots_snapshot()
        # declared serving chaos (ESTORCH_CHAOS): same plan + ledger as
        # training faults, keyed on seconds since the fleet armed
        for ev in _chaos.serve_faults(now - self._armed_mono):
            idx = int(ev.get("replica", 0))
            if not 0 <= idx < len(slots):
                continue
            slot = slots[idx]
            proc = slot.proc
            if proc is None or proc.poll() is not None:
                continue
            if ev["kind"] == "kill_replica":
                os.kill(proc.pid, signal.SIGKILL)
                self._event("chaos_kill_replica", replica=slot.name,
                            pid=proc.pid)
            else:  # wedge_replica: alive process, silent socket
                os.kill(proc.pid, signal.SIGSTOP)
                self._event("chaos_wedge_replica", replica=slot.name,
                            pid=proc.pid)
        router_health = {r.name: r.health
                        for r in self.router.replicas()}
        for slot in slots:
            if slot.state == "retiring":
                continue  # the scale thread owns its drain + reap
            if slot.state == "starting":
                if slot.proc is not None and slot.proc.poll() is not None:
                    self._event("replica_died", replica=slot.name,
                                exitcode=slot.proc.returncode,
                                during="startup")
                    self._schedule_respawn(slot)
                else:
                    self._check_starting(slot)
                continue
            if slot.state == "up":
                if slot.proc is not None and slot.proc.poll() is not None:
                    self._event("replica_died", replica=slot.name,
                                exitcode=slot.proc.returncode)
                    self._schedule_respawn(slot)
                    continue
                # wedge escalation: process alive, router polls failing
                h = router_health.get(slot.name) or {}
                down = h.get("polled") and not h.get("ok")
                if down:
                    if slot.down_since is None:
                        with self._slots_lock:
                            slot.down_since = now
                    elif now - slot.down_since > self.wedge_kill_s:
                        self.router.counters.inc(
                            "fleet_wedge_kills_total")
                        self._kill_slot(slot, reason="wedged")
                        self._schedule_respawn(slot)
                else:
                    with self._slots_lock:
                        slot.down_since = None
                continue
            # down: respawn when the backoff expires (bounded)
            if slot.restarts > self.max_restarts:
                continue
            if now >= slot.next_spawn_at:
                self._spawn(slot)

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — the monitor IS the
                # supervisor: dying silently would orphan every replica,
                # so a tick bug is recorded and the loop keeps watching
                self.router.counters.inc("fleet_monitor_errors_total")
                self._event("monitor_error", error=repr(e)[:300])
            self._stop.wait(0.2)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._armed_mono = time.monotonic()
        for slot in self.slots:
            self._spawn(slot)
        self.router.start_background()
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True)
        self._monitor_thread.start()

    def arm_chaos(self) -> None:
        """Re-zero the serve-chaos clock: ``at_s`` offsets count from
        this call instead of :meth:`start`.  A test that schedules
        ``kill_replica@2s`` almost always means two seconds of SERVING,
        not two seconds into the jax-import storm — call this after
        :meth:`wait_ready`."""
        self._armed_mono = time.monotonic()

    def wait_ready(self, timeout_s: float = START_TIMEOUT_S) -> bool:
        """Block until every slot is up (True) or the timeout passes.
        On readiness, each slot's ``/stats`` cold-start facts are
        recorded (``slot.cold_start``): the INITIAL spawn gets the same
        warmth proof as respawns — ``compiles_at_load == 0``."""
        deadline = time.monotonic() + float(timeout_s)
        ready = False
        while time.monotonic() < deadline:
            if all(s.state == "up" for s in self._slots_snapshot()):
                ready = True
                break
            if self._stop.wait(0.1):
                return False
        ready = ready or all(s.state == "up"
                             for s in self._slots_snapshot())
        if ready:
            for slot in self._slots_snapshot():
                if slot.cold_start is None:
                    self._record_cold_start(slot)
        return ready

    def _record_cold_start(self, slot: _Slot) -> dict | None:
        """Pin the replica's ``/stats`` ``cold_start`` block on its slot
        (best-effort: a momentarily-slow replica is still up)."""
        addr = slot.address
        if addr is None:
            return None
        host, _, port = addr.partition(":")
        try:
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=10.0)
        except ValueError:
            return None
        try:
            conn.request("GET", "/stats")
            stats = json.loads(conn.getresponse().read().decode())
            cold = stats.get("cold_start")
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            conn.close()
        if not isinstance(cold, dict):
            return None
        with self._slots_lock:
            slot.cold_start = cold
        return cold

    def shutdown(self) -> dict:
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=10)
        final = self.router.shutdown(drain=True)
        slots = self._slots_snapshot()
        for slot in slots:
            proc = slot.proc
            if proc is not None and proc.poll() is None:
                # SIGCONT first: a chaos-SIGSTOPped replica cannot drain
                try:
                    os.kill(proc.pid, signal.SIGCONT)
                except OSError:
                    pass
                proc.terminate()
        deadline = time.monotonic() + 30.0
        for slot in slots:
            proc = slot.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1,
                                      deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    self._event("replica_unreapable", replica=slot.name)
        return final

    def status(self) -> dict:
        with self._ro_lock:
            ro = {"state": self._ro_state, "last": self._ro_result}
        snap = self._slots_snapshot()
        return {
            "bundle": self.bundle,
            "replicas": [{
                "name": s.name, "state": s.state, "address": s.address,
                "restarts": s.restarts,
                "pid": s.proc.pid if s.proc else None,
                "cold_start": s.cold_start,
            } for s in snap],
            "scale": {"desired": self.desired,
                      "actual": sum(1 for s in snap
                                    if s.state == "up")},
            "rollout": ro,
            "events": self.events[-50:],
        }

    # ------------------------------------------------------------- scaling

    def scale_bounds(self) -> tuple[int, int]:
        az = self.autoscale_cfg or {}
        return (int(az.get("min_replicas", 1)),
                int(az.get("max_replicas", 64)))

    def _bundle_identity(self) -> dict:
        """The incumbent bundle's identity facts (MANIFEST.json, read
        jax-free) — what the autoscaler compares its capacity model
        against before touching the fleet."""
        out = {"bundle": self.bundle, "bundle_sha": None,
               "bundle_version": None, "platform": None}
        try:
            with open(os.path.join(self.bundle, "MANIFEST.json")) as f:
                man = json.load(f)
        except (OSError, ValueError):
            return out
        out["bundle_version"] = man.get("version")
        out["bundle_sha"] = (man.get("sha256") or {}).get("arrays.npz")
        out["platform"] = (man.get("warm") or {}).get("platform")
        return out

    def scale_status(self) -> dict:
        snap = self._slots_snapshot()
        lo, hi = self.scale_bounds()
        return {
            "autoscale": bool(self.autoscale_cfg),
            "desired": self.desired,
            "actual": sum(1 for s in snap if s.state == "up"),
            "slots": [{"name": s.name, "state": s.state} for s in snap],
            "min": lo, "max": hi,
            "in_progress": self._scale_lock.locked(),
            "last": self._last_scale,
            **self._bundle_identity(),
        }

    def _scale_cb(self, op: str, data: dict | None) -> dict:
        """The router's /scale delegate: validate, then actuate on a
        dedicated thread — the admin POST answers immediately (the
        autoscaler's decision log records ACCEPTANCE; convergence is
        observable via GET /scale and the store's gauges)."""
        if op == "status":
            return self.scale_status()
        try:
            n = int((data or {})["replicas"])
        except (KeyError, TypeError, ValueError):
            return {"ok": False,
                    "error": "scale needs {'replicas': <int >= 1>}"}
        if self._scale_lock.locked():
            return {"ok": False, "error": "scale already in progress",
                    "desired": self.desired}
        lo, hi = self.scale_bounds()
        clamped = min(max(n, lo), hi)
        cur = len(self._slots_snapshot())
        if clamped == cur and clamped == self.desired:
            return {"ok": True, "noop": True, "desired": clamped,
                    "from": cur}
        reason = str((data or {}).get("reason") or "api")
        t = threading.Thread(target=self.scale_to, args=(clamped,),
                             kwargs={"reason": reason},
                             name="fleet-scale", daemon=True)
        t.start()
        return {"ok": True, "accepted": True, "desired": clamped,
                "from": cur, "clamped": clamped != n}

    def scale_to(self, replicas: int, *, reason: str = "api") -> dict:
        """Converge the fleet to ``replicas`` slots (clamped to the
        autoscale bounds).  Synchronous: returns once added slots are up
        (with their warmth proof) and removed slots are drained, dead
        and forgotten."""
        lo, hi = self.scale_bounds()
        n = min(max(int(replicas), lo), hi)
        t0 = time.monotonic()
        with self._scale_lock:
            with self._ro_lock:
                ro_busy = self._ro_state != "idle"
            if ro_busy:
                # a rollout owns replica membership semantics (canary
                # quarantine); scaling under it could retire the canary
                return {"ok": False, "error": "rollout in progress"}
            cur = len(self._slots_snapshot())
            self.desired = n
            self.router.desired_replicas = n
            result: dict = {"ok": True, "desired": n, "from": cur,
                            "requested": int(replicas), "reason": reason,
                            "added": [], "retired": [],
                            "ts": time.time()}
            if n > cur:
                new_slots = []
                with self._slots_lock:
                    for _ in range(n - cur):
                        slot = _Slot(self._next_index, self.workdir)
                        self._next_index += 1
                        self.slots.append(slot)
                        new_slots.append(slot)
                for slot in new_slots:
                    self._event("scale_up", replica=slot.name,
                                reason=reason)
                    self._spawn(slot)
                # warm gate: every added slot must arrive with ZERO
                # fresh XLA builds (the bundle ships a warm cache —
                # scale-up capacity that compiles on arrival is late)
                deadline = time.monotonic() + self.start_timeout_s
                for slot in new_slots:
                    while (slot.state != "up"
                           and time.monotonic() < deadline):
                        if self._stop.wait(0.1):
                            break
                    cold = (self._record_cold_start(slot)
                            if slot.state == "up" else None)
                    compiles = (cold or {}).get("compiles_at_load")
                    result["added"].append({
                        "replica": slot.name, "state": slot.state,
                        "compiles_at_load": compiles})
                    if compiles == 0:
                        self._event("scale_up_warm", replica=slot.name)
                    else:
                        self.router.counters.inc(
                            "fleet_cold_scale_ups_total")
                        self._event("scale_up_cold", replica=slot.name,
                                    compiles_at_load=compiles)
            elif n < cur:
                for _ in range(cur - n):
                    res = self._retire_one(reason)
                    result["retired"].append(res)
                    if not res.get("ok"):
                        result["ok"] = False
                        break
            result["duration_s"] = round(time.monotonic() - t0, 3)
            self._last_scale = result
            self._event("scale_done", desired=n,
                        ok=result["ok"],
                        added=[a["replica"] for a in result["added"]],
                        retired=[r.get("replica")
                                 for r in result["retired"]])
            return result

    def _retire_one(self, reason: str) -> dict:
        """Drain-then-retire the least-loaded up replica: deselect in
        the router FIRST (no new request can reach it), wait for
        router-side in-flight to hit zero, SIGTERM (the replica's own
        drain answers its internal queue and exits 0), reap, forget."""
        import contextlib

        up = [s for s in self._slots_snapshot() if s.state == "up"]
        if len(up) <= 1:
            return {"ok": False, "error": "nothing retirable "
                                          "(<= 1 replica up)"}
        reps = {r.name: r for r in self.router.replicas()}

        def load_of(slot: _Slot) -> float:
            rep = reps.get(slot.name)
            if rep is None:
                return 0.0
            q = rep.health.get("queue_depth")
            return (0.0 if q is None else float(q)) + rep.inflight

        slot = min(up, key=load_of)
        with self._slots_lock:
            slot.state = "retiring"
        self.router.retire_replica(slot.name)
        self._event("replica_retiring", replica=slot.name, reason=reason)
        rep = reps.get(slot.name)
        drained = True
        deadline = time.monotonic() + RETIRE_INFLIGHT_WAIT_S
        while rep is not None and rep.inflight > 0:
            if time.monotonic() > deadline or self._stop.wait(0.05):
                drained = False
                break
        proc = slot.proc
        exitcode = None
        if proc is not None and proc.poll() is None:
            with contextlib.suppress(OSError):
                os.kill(proc.pid, signal.SIGCONT)  # a wedged corpse
                # cannot run its SIGTERM drain handler
            proc.terminate()
            try:
                proc.wait(timeout=RETIRE_REAP_S)
            except subprocess.TimeoutExpired:
                drained = False
                proc.kill()
                with contextlib.suppress(subprocess.TimeoutExpired):
                    proc.wait(timeout=10)
        if proc is not None:
            exitcode = proc.returncode
        self.router.remove_replica(slot.name)
        with self._slots_lock:
            if slot in self.slots:
                self.slots.remove(slot)
        drained = drained and exitcode == 0
        self._event("replica_retired", replica=slot.name,
                    exitcode=exitcode, drained=drained)
        return {"ok": True, "replica": slot.name, "exitcode": exitcode,
                "drained": drained}

    # ------------------------------------------------------------- rollout

    def _rollout_cb(self, op: str, data: dict | None) -> dict:
        """The router's /rollout delegate."""
        if op == "status":
            return self.status()["rollout"] | {"fleet": True}
        path = os.path.abspath(str(data["path"]))
        with self._ro_lock:
            if self._ro_state != "idle":
                return {"ok": False,
                        "error": f"rollout already {self._ro_state}"}
            self._ro_state = "canary"
            self._ro_result = None
            self._ro_thread = threading.Thread(
                target=self._rollout_thread, args=(path, dict(data or {})),
                name="fleet-rollout", daemon=True)
            self._ro_thread.start()
        return {"ok": True, "state": "canary", "path": path}

    def _reload_replica(self, slot: _Slot, path: str,
                        timeout_s: float = 300.0) -> str | None:
        """POST /reload to one replica; returns an error string or None.
        Never retried: /reload is non-idempotent (a replayed reload
        double-swaps engines)."""
        if slot.address is None:
            return "replica has no address"
        host, _, port = slot.address.partition(":")
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=timeout_s)
        try:
            body = json.dumps({"path": path}).encode()
            conn.request("POST", "/reload", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return (f"{resp.status}: "
                        f"{data[:300].decode(errors='replace')}")
            return None
        except (OSError, http.client.HTTPException) as e:
            return f"{type(e).__name__}: {e}"
        finally:
            conn.close()

    def _pick_canary(self) -> _Slot | None:
        up = [s for s in self._slots_snapshot() if s.state == "up"]
        if len(up) < 2:
            return None  # shadow comparison needs a live incumbent
        return up[0]

    def _abort_rollout(self, canary: _Slot, incumbent: str, reason: str,
                       evidence: dict) -> dict:
        """Roll the canary back to the incumbent.  If even the rollback
        reload fails, kill the canary — the respawn path loads
        ``self.bundle`` (still the incumbent), which IS the rollback of
        last resort."""
        self.router.end_canary()
        err = self._reload_replica(canary, incumbent)
        rolled_back = "reload"
        if err is not None:
            self._kill_slot(canary, reason="rollback")
            self._schedule_respawn(canary)
            rolled_back = f"respawn (reload failed: {err})"
        result = {"ok": False, "aborted": True, "reason": reason,
                  "evidence": evidence, "rolled_back": rolled_back,
                  "canary": canary.name, "ts": time.time()}
        self.router.counters.inc("fleet_rollouts_aborted_total")
        self._event("rollout_aborted", reason=reason, canary=canary.name,
                    evidence=evidence)
        return result

    def _rollout_thread(self, path: str, req: dict) -> None:
        cfg = {**self.rollout_cfg,
               **{k: v for k, v in req.items() if k in ROLLOUT_DEFAULTS}}
        incumbent = self.bundle
        result: dict
        try:
            canary = self._pick_canary()
            if canary is None:
                result = {"ok": False, "aborted": True,
                          "reason": "insufficient_fleet",
                          "evidence": {"up": sum(
                              1 for s in self._slots_snapshot()
                              if s.state == "up")},
                          "ts": time.time()}
                self.router.counters.inc("fleet_rollouts_aborted_total")
                self._event("rollout_aborted",
                            reason="insufficient_fleet")
                return
            self._event("rollout_started", path=path,
                        canary=canary.name)
            # quarantine FIRST: from this moment no client request can
            # reach the canary, so the reload below can never leak an
            # unpromoted bundle's answers into live traffic
            self.router.start_canary(
                canary.name, cfg["shadow_fraction"],
                parity_max=int(cfg["parity_samples"]))
            err = self._reload_replica(canary, path)
            if err is not None:
                # the old bundle kept serving (reload's contract): no
                # rollback needed, the fleet never left the incumbent
                self.router.end_canary()
                result = {"ok": False, "aborted": True,
                          "reason": "canary_reload_failed",
                          "evidence": {"error": err},
                          "canary": canary.name, "ts": time.time()}
                self.router.counters.inc("fleet_rollouts_aborted_total")
                self._event("rollout_aborted",
                            reason="canary_reload_failed", error=err)
                return
            self.router.arm_canary()
            deadline = time.monotonic() + float(cfg["window_s"])
            need_parity = (int(cfg["parity_samples"])
                           if cfg["check_parity"] else 0)
            while time.monotonic() < deadline:
                snap = self.router.canary_snapshot()
                if snap is None:
                    break
                if (len(snap["canary_lat"]) >= int(cfg["min_shadow"])
                        and len(snap["parity"]) >= need_parity):
                    break
                if self._stop.wait(0.2):
                    break
            snap = self.router.end_canary() or {
                "canary_lat": [], "incumbent_lat": [], "parity": [],
                "shadow_sent": 0, "shadow_errors": 0, "shadow_dropped": 0}
            counts = {"shadow_sent": snap["shadow_sent"],
                      "shadow_errors": snap["shadow_errors"],
                      "canary_samples": len(snap["canary_lat"]),
                      "incumbent_samples": len(snap["incumbent_lat"]),
                      "parity_samples": len(snap["parity"])}
            if (len(snap["canary_lat"]) < int(cfg["min_shadow"])
                    or len(snap["parity"]) < need_parity
                    or not snap["incumbent_lat"]):
                result = self._abort_rollout(
                    canary, incumbent, "insufficient_traffic", counts)
                return
            # gate (b): bit parity — same obs rows, exact comparison
            if cfg["check_parity"]:
                mismatches = [
                    {"request": req_body[:200], "incumbent": live,
                     "canary": can}
                    for req_body, live, can in snap["parity"]
                    if live != can]
                if mismatches:
                    result = self._abort_rollout(
                        canary, incumbent, "parity", {
                            **counts,
                            "mismatched": len(mismatches),
                            "example": mismatches[0]})
                    return
            # gate (a): canary tail inside the learned band vs incumbent
            verdict = compare_tail(
                [{"endpoint": "/predict", "latency_s": v}
                 for v in snap["canary_lat"]],
                [{"endpoint": "/predict", "latency_s": v}
                 for v in snap["incumbent_lat"]],
                quantile=float(cfg["tail_quantile"]),
                min_band_pct=float(cfg["min_band_pct"]))
            if verdict["verdict"] != "pass":
                result = self._abort_rollout(
                    canary, incumbent, "tail_band", {
                        **counts,
                        "quantile": verdict["quantile"],
                        "groups": verdict["groups"]})
                return
            # promote fleet-wide (the canary already serves the new one)
            failures = {}
            for slot in self._slots_snapshot():
                if slot is canary or slot.state != "up":
                    continue
                err = self._reload_replica(slot, path)
                if err is not None:
                    failures[slot.name] = err
            if failures:
                # partial fleets are worse than either bundle: roll
                # everything (canary included) back to the incumbent
                for slot in self._slots_snapshot():
                    if slot.state != "up":
                        continue
                    if self._reload_replica(slot, incumbent) is not None:
                        self._kill_slot(slot, reason="rollback")
                        self._schedule_respawn(slot)
                result = {"ok": False, "aborted": True,
                          "reason": "promote_failed",
                          "evidence": {**counts, "failures": failures},
                          "canary": canary.name, "ts": time.time()}
                self.router.counters.inc("fleet_rollouts_aborted_total")
                self._event("rollout_aborted", reason="promote_failed",
                            failures=failures)
                return
            self.bundle = path
            result = {"ok": True, "promoted": True, "path": path,
                      "canary": canary.name,
                      "evidence": {**counts,
                                   "tail": verdict["groups"].get(
                                       "/predict")},
                      "ts": time.time()}
            self.router.counters.inc("fleet_rollouts_promoted_total")
            self._event("rollout_promoted", path=path)
        except Exception as e:  # noqa: BLE001 — a rollout bug must land
            # as an aborted result, never a silently-dead thread
            self.router.end_canary()
            result = {"ok": False, "aborted": True,
                      "reason": "internal_error",
                      "evidence": {"error": repr(e)[:300]},
                      "ts": time.time()}
            self.router.counters.inc("fleet_rollouts_aborted_total")
            self._event("rollout_aborted", reason="internal_error",
                        error=repr(e)[:300])
        finally:
            with self._ro_lock:
                self._ro_result = result
                self._ro_state = "idle"


# ------------------------------------------------------------------ CLI

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.serve route --fleet",
        description="serving-fleet supervisor: replicas + router + "
                    "canary rollout (docs/serving.md, 'Fleet')")
    p.add_argument("--fleet", required=True, metavar="PATH",
                   help="fleet.json (schema in docs/serving.md)")
    p.add_argument("--workdir", default=None, metavar="DIR",
                   help="port files / replica logs (default: "
                        "<fleet.json dir>/fleet_run)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8400,
                   help="router port (0 = ephemeral, see --port-file)")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="atomically write the ROUTER's {host,port,pid}")
    p.add_argument("--autoscale", action="store_true",
                   help="embed the autoscaler loop (obs/agg/autoscale.py)"
                        " in this supervisor; needs fleet.json's "
                        "autoscale block with 'store' and 'capacity'")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        config = load_fleet_config(args.fleet)
    except FleetError as e:
        print(f"fleet: {e}", file=sys.stderr)
        return 2
    workdir = args.workdir or os.path.join(
        os.path.dirname(os.path.abspath(args.fleet)), "fleet_run")
    fleet = Fleet(config, workdir, host=args.host, port=args.port)
    stop = threading.Event()

    def _on_signal(signum, frame):
        del frame
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    scaler = None
    if args.autoscale:
        az = config.get("autoscale") or {}
        if not az.get("store") or not az.get("capacity"):
            print("fleet: --autoscale needs fleet.json's autoscale block "
                  "with 'store' and 'capacity'", file=sys.stderr)
            return 2
        if __package__:
            from ..obs.agg import autoscale as _autoscale
        else:
            _autoscale = _load("_estorch_obs_autoscale", os.pardir,
                               "obs", "agg", "autoscale.py")
        policy = {k: v for k, v in az.items()
                  if k in _autoscale.POLICY_DEFAULTS}
        try:
            scaler = _autoscale.Autoscaler(
                az["store"], capacity=az["capacity"],
                actuate=lambda n, reason: fleet.scale_to(n,
                                                         reason=reason),
                fleet_identity=fleet._bundle_identity(),
                target=az.get("target"),
                interval_s=float(az.get("interval_s", 2.0)),
                policy=policy)
        except _autoscale.AutoscaleError as e:
            # the capacity-model refusal (mismatched bundle/platform,
            # unreadable artifact): never supervise with a wrong model
            print(f"fleet: autoscale refused: {e}", file=sys.stderr)
            return 2
    fleet.start()
    if scaler is not None:
        scaler.start_background()
    router = fleet.router
    print(json.dumps({
        "ready": True, "role": "fleet",
        "url": f"http://{router.host}:{router.port}",
        "pid": os.getpid(),
        "replicas": [s.name for s in fleet.slots],
        "bundle": fleet.bundle,
        "autoscale": scaler is not None,
    }), flush=True)
    if args.port_file:
        write_port_file(args.port_file, router.host, router.port)
    while not stop.wait(0.5):
        pass
    if scaler is not None:
        scaler.stop()
    final = fleet.shutdown()
    print(json.dumps(final, default=float), flush=True)
    return 0 if final["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
