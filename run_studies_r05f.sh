#!/bin/bash
# Final round-5 CPU ladder: capstone seed-robustness, then Humanoid-v5
# extension in short resumable stages (lock releases between stages so a
# TPU window can preempt the queue).
set -u
cd /root/repo
LOCK=/root/repo/.evidence.lock
LOG=/root/repo/studies_r05f.log
stage() {
  echo "--- stage: $*" >> "$LOG"
  flock "$LOCK" "$@" >> "$LOG" 2>&1
  echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
}
stage /opt/venv/bin/python examples/capstone_run.py humanoid2d_device 1000 100 1
stage /opt/venv/bin/python examples/humanoid_v3_pooled.py 15 512 0 --resume
stage /opt/venv/bin/python examples/humanoid_v3_pooled.py 30 512 0 --resume
stage /opt/venv/bin/python examples/humanoid_v3_pooled.py 45 512 0 --resume
stage /opt/venv/bin/python examples/humanoid_v3_pooled.py 60 512 0 --resume
echo "queue done $(date -u +%FT%TZ)" >> "$LOG"
