#!/bin/bash
cd /root/repo
LOG=/root/repo/validation_r05.log
echo "--- stage: dryrun_multichip(8) post-recurrent-changes" >> "$LOG"
flock /root/repo/.evidence.lock /opt/venv/bin/python -c "
import __graft_entry__ as g
g.dryrun_multichip(8)
print('dryrun_multichip(8) OK')" >> "$LOG" 2>&1
echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
